"""True multi-process distributed execution: two OS processes form one
jax.distributed job, run the same SPMD consensus sweep, and must return
identical replicated results with coordinator-only file writes — the
cross-host contract documented in nmfx/distributed.py, which
single-process mesh tests cannot exercise."""

import json
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    from nmfx._compat import force_cpu_devices
    force_cpu_devices(4)
    coord, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    import nmfx
    import nmfx.distributed as dist
    dist.initialize(coordinator_address=coord, num_processes=2,
                    process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8
    import numpy as np
    from nmfx.config import SolverConfig
    from nmfx.datasets import two_group_matrix
    a = two_group_matrix(n_genes=80, n_per_group=8, seed=1)
    # per-process output dir: only the coordinator's may appear
    result = dist.consensus(
        a, ks=(2, 3), restarts=8, seed=5,
        solver_cfg=SolverConfig(max_iter=150),
        output=nmfx.OutputConfig(
            directory=os.path.join(outdir, f"files{pid}"),
            write_plots=False))
    payload = {"summary": result.summary(),
               "consensus2": np.asarray(result.per_k[2].consensus).tolist()}
    with open(os.path.join(outdir, f"proc{pid}.json"), "w") as f:
        json.dump(payload, f)
""")

_GRID_WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    # 2 devices per process -> 4 global: a (1, 2, 2) grid mesh then puts
    # the FEATURE axis across the two processes (jax.devices() is
    # process-major), so the per-iteration feature psums genuinely cross
    # the process boundary — the DCN analogue. (With 4 devices per
    # process and a restart axis of 2, each factorization's grid would
    # sit wholly inside one process and test nothing new.)
    from nmfx._compat import force_cpu_devices
    force_cpu_devices(2)
    coord, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    import nmfx.distributed as dist
    dist.initialize(coordinator_address=coord, num_processes=2,
                    process_id=pid)
    assert len(jax.devices()) == 4
    import numpy as np
    from nmfx.datasets import two_group_matrix
    a = two_group_matrix(n_genes=80, n_per_group=8, seed=1)
    result = dist.consensus(
        a, ks=(2,), restarts=4, seed=5, algorithm="kl", max_iter=150,
        feature_shards=2, sample_shards=2)
    payload = {"summary": result.summary(),
               "consensus2": np.asarray(result.per_k[2].consensus).tolist()}
    with open(os.path.join(outdir, f"grid{pid}.json"), "w") as f:
        json.dump(payload, f)
""")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(worker_src: str, tmp_path, out_prefix: str):
    """Launch two worker processes forming one jax.distributed job; return
    their per-process JSON payloads."""
    worker = tmp_path / f"{out_prefix}_worker.py"
    worker.write_text(worker_src)
    coord = f"localhost:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(worker), coord, str(i), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=str(tmp_path)) for i in range(2)]
    errs = []
    for p in procs:
        try:
            _, e = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            _, e = p.communicate()
        if p.returncode != 0:
            errs.append(e[-3000:])
    if errs and all("Multiprocess computations aren't implemented"
                    in e for e in errs):
        # old jaxlibs' CPU backend has no cross-process collectives at
        # all — the contract under test cannot exist here (it is
        # exercised for real on TPU pods); newer jaxlibs run it via the
        # virtual-device CPU platform
        import pytest

        pytest.skip("this jaxlib's CPU backend lacks multi-process "
                    "collectives")
    assert not errs, errs
    return [json.loads((tmp_path / f"{out_prefix}{i}.json").read_text())
            for i in range(2)]


def test_two_process_distributed_consensus(tmp_path):
    r0, r1 = _run_workers(_WORKER, tmp_path, "proc")
    # replicated-output contract: every host computes the identical result
    assert r0["summary"] == r1["summary"]
    assert r0["consensus2"] == r1["consensus2"]
    assert "best k = 2" in r0["summary"]
    # coordinator-only writes: process 0's dir has the outputs, process 1's
    # was never created (dist.consensus nulls output off-coordinator)
    files = os.listdir(tmp_path / "files0")
    assert "cophenetic.txt" in files
    assert not (tmp_path / "files1").exists()


_EXEC_WRITER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                             SolverConfig)
    from nmfx import exec_cache as ec

    cache_dir, out_path = sys.argv[1], sys.argv[2]
    a = np.random.default_rng(0).uniform(0.1, 1.0, (60, 20))
    cache = ec.ExecCache(ExecCacheConfig(cache_dir=cache_dir))
    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=3, grid_exec="grid",
                           grid_slots=2)
    res = cache.run_sweep(a, ccfg, SolverConfig(max_iter=20), InitConfig())
    with open(out_path, "w") as f:
        json.dump({"labels": np.asarray(res[2].labels).tolist(),
                   "compiles": ec.compile_count()}, f)
""")


def test_exec_cache_concurrent_writers_leave_valid_cache(tmp_path):
    """Two OS processes cold-starting the SAME exec-cache entry
    concurrently both publish via atomic tmp+rename: exactly one valid
    entry file survives (last wins), no partial temp files leak, and a
    subsequent reader deserializes it compile-free."""
    cache_dir = tmp_path / "exec"
    cache_dir.mkdir()
    script = tmp_path / "exec_writer.py"
    script.write_text(_EXEC_WRITER)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(cache_dir),
         str(tmp_path / f"writer{i}.json")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(2)]
    errs = []
    for p in procs:
        try:
            _, e = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            _, e = p.communicate()
        if p.returncode != 0:
            errs.append(e[-3000:])
    assert not errs, errs
    payloads = [json.loads((tmp_path / f"writer{i}.json").read_text())
                for i in range(2)]
    # both raced through a cold compile and produced identical results
    assert all(pl["compiles"] >= 1 for pl in payloads)
    assert payloads[0]["labels"] == payloads[1]["labels"]
    names = os.listdir(cache_dir)
    assert len([n for n in names if n.endswith(".nmfxexec")]) == 1
    assert not [n for n in names if n.endswith(".part")]
    # the surviving entry is a valid, complete record this process can
    # deserialize and serve from — no recompile
    from nmfx import exec_cache as ec
    from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                             SolverConfig)

    cache = ec.ExecCache(ExecCacheConfig(cache_dir=str(cache_dir)))
    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=3, grid_exec="grid",
                           grid_slots=2)
    _, hit = cache.executable((60, 20), ccfg, SolverConfig(max_iter=20),
                              InitConfig())
    assert hit and cache.stats["persist_hits"] == 1 and cache.misses == 0


def test_two_process_grid_axes(tmp_path):
    """Feature-axis collectives spanning the process boundary: a (1, 2, 2)
    grid mesh over two OS processes running the kl grid driver — every
    iteration's feature psums cross processes."""
    r0, r1 = _run_workers(_GRID_WORKER, tmp_path, "grid")
    assert r0["summary"] == r1["summary"]
    assert r0["consensus2"] == r1["consensus2"]
    assert "best k = 2" in r0["summary"]


_READMIT_RACER = textwrap.dedent("""
    import json, os, sys, time
    spill_dir, out_path, go_path = sys.argv[1], sys.argv[2], sys.argv[3]
    from nmfx.serve import NMFXServer, ServeConfig, list_spills

    class _InertEngine:
        # readmit only ENQUEUES (the server stays paused); no dispatch
        # ever runs, so the race is purely over the claim protocol
        def compatibility_key(self, req):
            return None

        def place(self, req):
            return None

        def dispatch_solo(self, req, placed, scfg):
            raise AssertionError("paused server must not dispatch")

        def dispatch_packed(self, reqs, placed):
            raise AssertionError("paused server must not dispatch")

    srv = NMFXServer(ServeConfig(max_queue_depth=1000),
                     engine=_InertEngine(), start=False)
    while not os.path.exists(go_path):
        time.sleep(0.002)
    admitted = 0
    deadline = time.time() + 60
    while time.time() < deadline:
        admitted += len(srv.readmit(spill_dir))
        # records claimed by the peer stay on disk until IT removes
        # them — spin until the directory is fully consumed
        if not list_spills(spill_dir):
            break
        time.sleep(0.002)
    from nmfx.obs import flight
    origins = sorted(e["origin_request_id"]
                     for e in flight.default_recorder()
                     .events("serve.readmit"))
    assert len(origins) == admitted
    with open(out_path, "w") as f:
        json.dump({"origins": origins}, f)
    srv.close(cancel_pending=True)
""")


def test_two_process_readmit_claim_race(tmp_path):
    """The ISSUE 15 spill-claim satellite: two OS processes racing
    ``NMFXServer.readmit`` over ONE spill directory partition the
    records exactly — every record readmitted exactly once, never
    twice (the O_EXCL claim protocol), and nothing left behind."""
    import time

    import numpy as np

    from nmfx.config import InitConfig, SolverConfig
    from nmfx.serve import spill_meta, write_spill_record

    spill = tmp_path / "spill"
    spill.mkdir()
    n = 8
    for i in range(n):
        meta = spill_meta(request_id=i, ks=(2,), restarts=2, seed=i,
                          scfg=SolverConfig(), icfg=InitConfig(),
                          col_names=("a", "b"))
        write_spill_record(str(spill / f"spill_{i}.npz"),
                           np.ones((3, 2), np.float32), meta)
    racer = tmp_path / "racer.py"
    racer.write_text(_READMIT_RACER)
    go = tmp_path / "go"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(racer), str(spill),
         str(tmp_path / f"racer{i}.json"), str(go)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(2)]
    time.sleep(1.0)  # let both import; then release them together
    go.write_text("go")
    errs = []
    for p in procs:
        try:
            _, e = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            _, e = p.communicate()
        if p.returncode != 0:
            errs.append(e[-3000:])
    assert not errs, errs
    payloads = [json.loads((tmp_path / f"racer{i}.json").read_text())
                for i in range(2)]
    all_origins = payloads[0]["origins"] + payloads[1]["origins"]
    # exactly-once: every record admitted by exactly one consumer
    assert sorted(all_origins) == list(range(n)), payloads
    assert set(payloads[0]["origins"]).isdisjoint(
        payloads[1]["origins"])
    assert os.listdir(spill) == []  # records and claims all consumed
