"""Semantics of the noise-tolerant class-stability stop (SolverConfig.
class_flip_tol): the snapshot rule must (a) reproduce the reference's
consecutive-check rule exactly at tolerance 0 (reference nmf_mu.c:253-282),
(b) tolerate bounded label oscillation, and (c) still reset on slow genuine
drift — the case a naive "allow <= delta flips vs the previous check" rule
gets wrong (drift of 1 sample/check would count as stable forever).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from nmfx._compat import shard_map
from nmfx.config import SolverConfig
from nmfx.ops import packed_mu as pm
from nmfx.solvers import base

N, K = 10, 3


def _packed_state(labels: np.ndarray, it: int, prev: pm.PackedState | None,
                  r: int) -> pm.PackedState:
    """PackedState whose hp one-hot encodes `labels` (r, N); bookkeeping
    carried over from `prev`."""
    hp = np.zeros((r * K, N), np.float32)
    for lane in range(r):
        for j, lab in enumerate(labels[lane]):
            hp[lane * K + lab, j] = 1.0
    z = jnp.zeros((r,), jnp.int32)
    return pm.PackedState(
        wp=jnp.zeros((4, r * K)), hp=jnp.asarray(hp),
        wp_prev=jnp.zeros((4, r * K)), hp_prev=jnp.asarray(hp),
        iteration=jnp.asarray(it, jnp.int32),
        classes=(prev.classes if prev is not None
                 else jnp.full((r, N), -1, jnp.int32)),
        stable=prev.stable if prev is not None else z,
        done=prev.done if prev is not None else jnp.zeros((r,), bool),
        done_iter=prev.done_iter if prev is not None else z,
        stop_reason=prev.stop_reason if prev is not None else z)


def drive(label_frames, cfg: SolverConfig) -> np.ndarray:
    """Feed a sequence of (r, N) label frames through _check (one frame per
    check, iteration = 2, 4, 6, ...); return per-lane fire check index (the
    1-based frame at which done flipped) or -1."""
    r = label_frames[0].shape[0]
    state = None
    fired = np.full((r,), -1)
    for i, frame in enumerate(label_frames):
        state = _packed_state(np.asarray(frame), 2 * (i + 1), state, r)
        state = pm._check(state, cfg, r)
        newly = np.asarray(state.done) & (fired < 0)
        fired[newly] = i + 1
    return fired


def frames_oscillate(n_frames):
    """One boundary sample (column 0) alternates labels every check; the
    rest are fixed."""
    out = []
    for i in range(n_frames):
        lab = np.array([0, 0, 0, 1, 1, 1, 2, 2, 2, 2])
        lab[0] = i % 2
        out.append(lab[None, :])
    return out


def frames_drift(n_frames):
    """One additional sample migrates to label 2 every check — slow genuine
    drift at exactly 1 flip/check."""
    out = []
    for i in range(n_frames):
        lab = np.array([0, 0, 0, 1, 1, 1, 2, 2, 2, 2])
        lab[:min(i, 6)] = 2
        out.append(lab[None, :])
    return out


def test_strict_matches_consecutive_rule():
    """tol=0: stable frames fire after exactly stable_checks checks; a
    single flip anywhere resets the counter."""
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.0,
                       use_tol_checks=False)
    const = np.tile(np.array([0, 0, 0, 1, 1, 1, 2, 2, 2, 2]), (1, 1))
    # frame 1 resets the initial -1 snapshot; stable hits 5 at frame 6
    assert drive([const] * 8, cfg)[0] == 6
    # a flip at frame 3 resets twice (entering and leaving the flipped
    # state — frames 3 and 4 each differ from their predecessor), exactly
    # like the reference's consecutive-check rule: fire at 4 + 5
    frames = [const] * 10
    flipped = const.copy()
    flipped[0, 0] = 1
    frames[2] = flipped
    assert drive(frames, cfg)[0] == 9


def test_strict_never_fires_under_oscillation():
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.0,
                       use_tol_checks=False)
    assert drive(frames_oscillate(40), cfg)[0] == -1


def test_tolerant_fires_under_bounded_oscillation():
    # floor(0.2 * 10) = 2 tolerated flips
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.2,
                       use_tol_checks=False)
    # first frame resets the -1 snapshot; fire 5 checks later
    assert drive(frames_oscillate(40), cfg)[0] == 6


def test_tolerant_resets_on_genuine_drift():
    """1 flip/check cumulative drift must NOT count as stable even though
    each check is within tolerance of the *previous* one: mismatch vs the
    held snapshot accumulates past floor(0.2*10)=2 and resets."""
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.2,
                       use_tol_checks=False)
    fired = drive(frames_drift(7), cfg)
    assert fired[0] == -1


def test_tolerant_fires_after_drift_settles():
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.2,
                       use_tol_checks=False)
    frames = frames_drift(20)  # drift ends at frame 6, stable afterwards
    fired = drive(frames, cfg)
    assert fired[0] > 6  # fired only after the drift settled


def test_per_lane_independence():
    """A stable lane fires while an oscillating lane in the same packed
    batch does not (strict rule)."""
    cfg = SolverConfig(stable_checks=5, check_every=2, class_flip_tol=0.0,
                       use_tol_checks=False)
    osc = frames_oscillate(12)
    const = np.array([0, 0, 0, 1, 1, 1, 2, 2, 2, 2])
    frames = [np.stack([const, o[0]]) for o in osc]
    fired = drive(frames, cfg)
    assert fired[0] == 6 and fired[1] == -1


def test_base_driver_same_semantics():
    """The vmapped generic driver's check_convergence implements the same
    snapshot rule (scalar per restart)."""
    cfg = SolverConfig(stable_checks=4, check_every=2, class_flip_tol=0.2,
                       use_tol_checks=False)

    def h_of(lab):
        h = np.zeros((K, N), np.float32)
        h[lab, np.arange(N)] = 1.0
        return jnp.asarray(h)

    state = base.init_state(jnp.zeros((4, N)), jnp.zeros((4, K)),
                            h_of(np.zeros(N, int)), aux=None)
    fired_at = -1
    for i, frame in enumerate(frames_oscillate(30)):
        state = state._replace(h=h_of(frame[0]),
                               iteration=jnp.asarray(2 * (i + 1), jnp.int32))
        state = base.check_convergence(state, cfg, use_class=True)
        if bool(state.done) and fired_at < 0:
            fired_at = i + 1
    assert fired_at == 5  # snapshot set at frame 1, 4 stable checks after

    # strict never fires on the same sequence
    cfg0 = SolverConfig(stable_checks=4, check_every=2, class_flip_tol=0.0,
                        use_tol_checks=False)
    state = base.init_state(jnp.zeros((4, N)), jnp.zeros((4, K)),
                            h_of(np.zeros(N, int)), aux=None)
    for i, frame in enumerate(frames_oscillate(30)):
        state = state._replace(h=h_of(frame[0]),
                               iteration=jnp.asarray(2 * (i + 1), jnp.int32))
        state = base.check_convergence(state, cfg0, use_class=True)
    assert not bool(state.done)


def test_flip_tol_validation():
    with pytest.raises(ValueError):
        SolverConfig(class_flip_tol=1.0)
    with pytest.raises(ValueError):
        SolverConfig(class_flip_tol=-0.1)


def test_flip_tol_floor_float_rounding():
    """int(0.3 * 10) is 2 in binary float; the documented floor(tol*n) is 3.
    Exactly 3 mismatches at tol=0.3, n=10 must count as stable."""
    cfg = SolverConfig(stable_checks=3, check_every=2, class_flip_tol=0.3,
                       use_tol_checks=False)
    base_lab = np.array([0, 0, 0, 1, 1, 1, 2, 2, 2, 2])
    osc = base_lab.copy()
    osc[:3] = (osc[:3] + 1) % K  # 3 mismatches vs base
    frames = [base_lab[None, :]]
    frames += [osc[None, :] if i % 2 else base_lab[None, :]
               for i in range(8)]
    assert drive(frames, cfg)[0] > 0


def test_sharded_check_counts_global_mismatches():
    """Under shard_map with a sample axis, the mismatch count must be the
    global psum and the tolerance computed from the global n. The case is
    crafted so each shard's local count is within tolerance while the global
    sum exceeds it — a bug comparing local counts would pass."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    n_glob = 16
    r = 1
    devices = jax.devices()[:2]
    mesh = Mesh(np.array(devices), ("s",))
    # flip_tol = floor(0.15 * 16) = 2: 2 mismatches per shard -> global 4 > 2
    # must reset; a local-count bug would see 2 <= 2 on every shard and fire
    cfg = SolverConfig(stable_checks=3, check_every=2, class_flip_tol=0.15,
                       use_tol_checks=False)

    snap = np.zeros((r, n_glob), np.int32)
    cur = snap.copy()
    cur[0, [0, 1, 8, 9]] = 1  # 2 mismatches on each 8-column shard

    def one_hot_hp(labels):  # (r, n) -> (r*K, n)
        hp = np.zeros((r * K, labels.shape[1]), np.float32)
        for lane in range(r):
            for j, lab in enumerate(labels[lane]):
                hp[lane * K + lab, j] = 1.0
        return hp

    hp = jnp.asarray(one_hot_hp(cur))
    snap_j = jnp.asarray(snap)

    def body(hp_loc, snap_loc):
        st = pm.PackedState(
            wp=jnp.zeros((4, r * K)), hp=hp_loc,
            wp_prev=jnp.zeros((4, r * K)), hp_prev=hp_loc,
            iteration=jnp.asarray(4, jnp.int32),
            classes=snap_loc,
            stable=jnp.full((r,), 2, jnp.int32),  # one good check from firing
            done=jnp.zeros((r,), bool),
            done_iter=jnp.zeros((r,), jnp.int32),
            stop_reason=jnp.zeros((r,), jnp.int32))
        out = pm._check(st, cfg, r, sample_axis="s", n_total=n_glob)
        return out.stable, out.done

    stable, done = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(None, "s"), P(None, "s")),
        out_specs=(P(), P()), check_vma=False))(hp, snap_j)
    # 4 global mismatches > flip_tol=2: reset, no fire
    assert int(np.asarray(stable)[0]) == 0
    assert not bool(np.asarray(done)[0])

    # control: 2 global mismatches (1 per shard) <= 2: counter advances, fires
    cur2 = snap.copy()
    cur2[0, [0, 8]] = 1
    hp2 = jnp.asarray(one_hot_hp(cur2))
    stable2, done2 = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(None, "s"), P(None, "s")),
        out_specs=(P(), P()), check_vma=False))(hp2, snap_j)
    assert int(np.asarray(stable2)[0]) == 3
    assert bool(np.asarray(done2)[0])


def test_check_sharded_requires_n_total():
    cfg = SolverConfig(use_tol_checks=False)
    st = pm.PackedState(
        wp=jnp.zeros((4, K)), hp=jnp.zeros((K, N)),
        wp_prev=jnp.zeros((4, K)), hp_prev=jnp.zeros((K, N)),
        iteration=jnp.asarray(4, jnp.int32),
        classes=jnp.zeros((1, N), jnp.int32),
        stable=jnp.zeros((1,), jnp.int32),
        done=jnp.zeros((1,), bool),
        done_iter=jnp.zeros((1,), jnp.int32),
        stop_reason=jnp.zeros((1,), jnp.int32))
    with pytest.raises(ValueError, match="n_total"):
        pm._check(st, cfg, 1, sample_axis="s")
