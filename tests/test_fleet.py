"""Fleet observatory (ISSUE 14): telemetry publisher/collector merge
exactness, staleness semantics, torn-snapshot tolerance, SLO burn-rate
alerting, cross-process trace joins, and the nmfx-top dashboard.

The merge contracts are pinned EXACTLY (counter sums, bucket counts,
union-of-observations quantiles) — a fleet view that is "approximately"
the sum of its instances is a fleet view nothing can be gated on. The
subprocess rungs drive real OS-process publishers through the same
ledger; the heavyweight one is marked slow (tier-1 keeps a two-process
representative)."""

import json
import os
import subprocess
import sys
import time

import pytest

from nmfx import faults
from nmfx.obs import aggregate, export, metrics, slo, top, trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _pristine_faults():
    faults.disarm()
    faults._reset_warned()
    yield
    faults.disarm()
    faults._reset_warned()


def _registry_with(instance_idx: int, obs=()):
    """A fresh registry with one counter/gauge/histogram trio the merge
    tests drive."""
    reg = metrics.MetricsRegistry()
    c = reg.counter("nmfx_serve_dispatches_total", "d", ("packed",))
    c.inc(10 + instance_idx, packed="false")
    c.inc(2 * (instance_idx + 1), packed="true")
    g = reg.gauge("nmfx_serve_queue_depth", "q")
    g.set(3 + instance_idx)
    h = reg.histogram("nmfx_serve_e2e_seconds", "e", ("outcome",))
    for v in obs:
        h.observe(v, outcome="completed")
    return reg


def _publish(tmp_path, name, reg, role="server"):
    pub = export.TelemetryPublisher(str(tmp_path), instance=name,
                                    role=role, registry=reg)
    assert pub.publish_once() is not None
    return pub


# ---------------------------------------------------------------------
# merge exactness
# ---------------------------------------------------------------------

def test_fleet_counters_sum_and_gauges_key_by_instance(tmp_path):
    regs = [_registry_with(i) for i in range(3)]
    for i, reg in enumerate(regs):
        _publish(tmp_path, f"inst-{i}", reg)
    col = aggregate.FleetCollector(str(tmp_path))
    snap = col.fleet_snapshot()
    c = snap["nmfx_serve_dispatches_total"]
    assert c["series"][("false",)] == sum(10 + i for i in range(3))
    assert c["series"][("true",)] == sum(2 * (i + 1) for i in range(3))
    g = snap["nmfx_serve_queue_depth"]
    assert g["labels"] == ("instance",)
    assert g["series"] == {("inst-0",): 3.0, ("inst-1",): 4.0,
                           ("inst-2",): 5.0}
    # merged exposition renders through the shared formatter
    text = col.prometheus_text()
    assert 'nmfx_serve_queue_depth{instance="inst-1"} 4' in text
    assert "# TYPE nmfx_serve_dispatches_total counter" in text


def test_fleet_histogram_merge_equals_union_of_observations(tmp_path):
    """The pinned quantile contract: bucket-wise merge then quantile ==
    quantile of ONE histogram that observed every instance's
    observations."""
    import random

    rng = random.Random(7)
    all_obs = []
    for i in range(3):
        obs = [rng.uniform(0.0005, 40.0) for _ in range(120)]
        all_obs += obs
        _publish(tmp_path, f"inst-{i}", _registry_with(i, obs))
    union = metrics.MetricsRegistry().histogram(
        "union_seconds", "", ("outcome",))
    for v in all_obs:
        union.observe(v, outcome="completed")
    col = aggregate.FleetCollector(str(tmp_path))
    snap = col.fleet_snapshot()
    st = snap["nmfx_serve_e2e_seconds"]["series"][("completed",)]
    ust = union.series()[("completed",)]
    assert st["count"] == ust["count"] == len(all_obs)
    assert st["bucket_counts"] == ust["bucket_counts"]  # exact
    assert st["min"] == ust["min"] and st["max"] == ust["max"]
    for q in (0.0, 0.25, 0.5, 0.9, 0.99, 1.0):
        assert col.quantile("nmfx_serve_e2e_seconds", q, snapshot=snap,
                            outcome="completed") \
            == union.quantile(q, outcome="completed"), q


def test_fleet_delta_mirrors_registry_delta(tmp_path):
    reg = _registry_with(0, obs=[0.1, 0.2])
    pub = _publish(tmp_path, "inst-0", reg)
    col = aggregate.FleetCollector(str(tmp_path))
    prev = col.fleet_snapshot()
    reg.counter("nmfx_serve_dispatches_total", "d",
                ("packed",)).inc(5, packed="false")
    reg.histogram("nmfx_serve_e2e_seconds", "e",
                  ("outcome",)).observe(0.3, outcome="completed")
    pub.publish_once()
    delta = col.fleet_delta(prev)
    assert delta["nmfx_serve_dispatches_total"]["series"][
        ("false",)] == 5
    hd = delta["nmfx_serve_e2e_seconds"]["series"][("completed",)]
    assert hd["count"] == 1
    assert hd["sum"] == pytest.approx(0.3)


# ---------------------------------------------------------------------
# staleness + torn tolerance
# ---------------------------------------------------------------------

def test_stale_instance_keeps_counters_drops_gauges(tmp_path):
    _publish(tmp_path, "live", _registry_with(0))
    _publish(tmp_path, "dead", _registry_with(1))
    # age the dead instance's heartbeat INSIDE the payload (liveness is
    # the embedded time, not mtime)
    dead_path = export.snapshot_path(str(tmp_path), "dead")
    payload = json.load(open(dead_path))
    payload["time"] -= 3600.0
    json.dump(payload, open(dead_path, "w"))
    col = aggregate.FleetCollector(str(tmp_path), stale_after_s=10.0)
    rows = {r["instance"]: r for r in col.instances()}
    assert rows["live"]["stale"] is False
    assert rows["dead"]["stale"] is True
    snap = col.fleet_snapshot()
    # counters: monotone history that happened — both instances count
    assert snap["nmfx_serve_dispatches_total"]["series"][
        ("false",)] == 10 + 11
    # gauges: the dead replica's level no longer exists — dropped
    assert set(snap["nmfx_serve_queue_depth"]["series"]) == {("live",)}


def test_torn_and_foreign_snapshots_skipped_warn_once(tmp_path):
    _publish(tmp_path, "good", _registry_with(0))
    (tmp_path / "telemetry_torn.json").write_text('{"format": 1, "met')
    (tmp_path / "telemetry_foreign.json").write_text(
        '{"format": 999, "metrics": {}}')
    col = aggregate.FleetCollector(str(tmp_path))
    with pytest.warns(RuntimeWarning, match="fleet-snapshot-torn"):
        payloads = col.collect()
    assert set(payloads) == {"good"}
    # warn-once: the second collect is quiet, the skip persists
    assert set(col.collect()) == {"good"}


def test_conflicting_schema_skipped_warn_once(tmp_path):
    _publish(tmp_path, "a", _registry_with(0))
    reg_b = metrics.MetricsRegistry()
    reg_b.gauge("nmfx_serve_dispatches_total", "now a gauge!").set(9)
    _publish(tmp_path, "b", reg_b)
    col = aggregate.FleetCollector(str(tmp_path))
    with pytest.warns(RuntimeWarning, match="fleet-metric-conflict"):
        snap = col.fleet_snapshot()
    # instance a's counter survives; b's conflicting series skipped
    assert snap["nmfx_serve_dispatches_total"]["type"] == "counter"
    assert snap["nmfx_serve_dispatches_total"]["series"][
        ("false",)] == 10


# ---------------------------------------------------------------------
# publisher lifecycle + /metrics endpoint
# ---------------------------------------------------------------------

def test_publisher_thread_and_final_snapshot(tmp_path):
    reg = _registry_with(0)
    pub = export.TelemetryPublisher(str(tmp_path), instance="threaded",
                                    interval_s=0.05, registry=reg)
    with pub:
        deadline = time.monotonic() + 10
        while not os.path.exists(pub.path) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        reg.counter("nmfx_serve_dispatches_total", "d",
                    ("packed",)).inc(100, packed="false")
    # close() published a FINAL snapshot: the late increment landed
    payload = json.load(open(pub.path))
    series = {tuple(s["key"]): s["value"]
              for s in payload["metrics"][
                  "nmfx_serve_dispatches_total"]["series"]}
    assert series[("false",)] == 110


def test_serve_metrics_http_endpoint():
    import urllib.request

    reg = _registry_with(4)
    srv = export.serve_metrics(0, registry=reg)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics",
            timeout=10).read().decode()
    finally:
        srv.shutdown()
        srv.server_close()
    assert "# TYPE nmfx_serve_dispatches_total counter" in body
    assert 'nmfx_serve_dispatches_total{packed="false"} 14' in body


# ---------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------

def _slo_registry():
    reg = metrics.MetricsRegistry()
    h = reg.histogram("nmfx_serve_e2e_seconds", "e", ("outcome",))
    return reg, h


def test_availability_breach_flips_fast_burn_and_recovers():
    reg, h = _slo_registry()
    eng = slo.SLOEngine(
        objectives=(slo.Objective("availability",
                                  kind="availability"),),
        snapshot_fn=reg.snapshot)
    t0 = 1_000_000.0
    for _ in range(50):
        h.observe(0.1, outcome="completed")
    s = eng.evaluate(now=t0)
    assert s["objectives"]["availability"]["state"] == "ok"
    flight_before = len(_transitions())
    for _ in range(50):
        h.observe(0.1, outcome="failed")
    s = eng.evaluate(now=t0 + 300)
    avail = s["objectives"]["availability"]
    # the 50 completed landed BEFORE the baseline cut, so the window's
    # delta is 50 failed / 50 total: burn 1.0/0.01 = 100 >> 14.4 in
    # BOTH fast windows (history shorter than 1h falls back to the
    # oldest cut — lifetime burn)
    assert avail["state"] == "fast_burn"
    assert avail["burn"]["5m"] == pytest.approx(100.0)
    evs = _transitions()
    assert len(evs) == flight_before + 1
    assert evs[-1]["objective"] == "availability"
    assert evs[-1]["from_state"] == "ok"
    assert evs[-1]["to_state"] == "fast_burn"
    # recovery: a long clean stretch dilutes the short window to zero
    for _ in range(5000):
        h.observe(0.1, outcome="completed")
    eng.evaluate(now=t0 + 3600)
    s = eng.evaluate(now=t0 + 7800)
    assert s["objectives"]["availability"]["state"] == "ok"
    assert _transitions()[-1]["to_state"] == "ok"


def _transitions():
    from nmfx.obs import flight

    return flight.default_recorder().events("slo.transition")


def test_latency_objective_counts_over_bound_buckets():
    reg, h = _slo_registry()
    eng = slo.SLOEngine(
        objectives=(slo.Objective("lat", kind="latency", target=0.9,
                                  bound_s=1.0, budget=0.1),),
        snapshot_fn=lambda: slo.registry_snapshot(reg))
    t0 = 2_000_000.0
    eng.evaluate(now=t0)
    for _ in range(90):
        h.observe(0.01, outcome="completed")
    for _ in range(10):
        h.observe(30.0, outcome="completed")  # over the 1s bound
    s = eng.evaluate(now=t0 + 300)
    lat = s["objectives"]["lat"]
    # 10% over-bound against a 10% budget: burn exactly 1.0 — AT the
    # sustainable rate, which is not yet a breach (thresholds are
    # strict)
    assert lat["burn"]["5m"] == pytest.approx(1.0)
    assert lat["state"] == "ok"
    for _ in range(100):
        h.observe(30.0, outcome="completed")
    s = eng.evaluate(now=t0 + 600)
    lat = s["objectives"]["lat"]
    # 100% of the new window over-bound: burn 10 — over the slow
    # pair's 1x but under the fast pair's 14.4x (the multi-window
    # thresholds grade severity; the slow windows see the lifetime
    # 110/200 = burn 5.5, also over 1x)
    assert lat["burn"]["5m"] == pytest.approx(10.0)
    assert lat["state"] == "slow_burn"


def test_floor_objective_rate_and_zero_floor():
    reg, h = _slo_registry()
    eng = slo.SLOEngine(
        objectives=(slo.Objective("goodput", kind="floor",
                                  value="rate", floor=10.0,
                                  budget=0.25),
                    slo.Objective("disabled", kind="floor",
                                  value="rate", floor=0.0)),
        snapshot_fn=reg.snapshot)
    t0 = 3_000_000.0
    eng.evaluate(now=t0)
    for _ in range(30):  # 30 req / 300 s = 0.1 req/s << floor 10
        h.observe(0.1, outcome="completed")
    s = eng.evaluate(now=t0 + 300)
    assert s["objectives"]["goodput"]["burn"]["5m"] \
        == pytest.approx((10.0 - 0.1) / 10.0 / 0.25)
    # burn ~3.96: over the slow pair's 1x, under the fast pair's 14.4x
    assert s["objectives"]["goodput"]["state"] == "slow_burn"
    # a zero floor never burns — shipped-default objectives stay
    # visible without paging anyone
    assert s["objectives"]["disabled"]["burn"]["5m"] == 0.0
    assert s["objectives"]["disabled"]["state"] == "ok"


def test_server_stats_snapshot_carries_slo_status():
    from nmfx.serve import NMFXServer, ServeConfig

    srv = NMFXServer(ServeConfig(), engine=object(), start=False)
    try:
        status = srv.stats_snapshot()["slo"]
        assert set(status["objectives"]) == {
            "availability", "latency_p99", "goodput", "mfu"}
        for obj in status["objectives"].values():
            assert obj["state"] == "ok"
    finally:
        srv.close()


# ---------------------------------------------------------------------
# cross-process traces: merge + spill/readmit id joins
# ---------------------------------------------------------------------

def test_merge_traces_aligns_on_wall_clock_anchor(tmp_path):
    tr_a, tr_b = trace.Tracer(), trace.Tracer()
    tr_a.enabled = tr_b.enabled = True
    tr_a._t0_epoch -= 10.0  # process A started 10s earlier
    with tr_a.span("a.work"):
        pass
    with tr_b.span("b.work"):
        pass
    pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    tr_a.export(pa)
    tr_b.export(pb)
    merged = trace.merge_traces([pa, pb],
                                path=str(tmp_path / "merged.json"))
    on_disk = json.load(open(tmp_path / "merged.json"))
    assert on_disk["metadata"]["nmfx_merged"] == 2
    xs = {e["name"]: e["ts"] for e in merged["traceEvents"]
          if e.get("ph") == "X"}
    # A's span lands ~10s (1e7 us) before B's on the shared axis
    assert xs["b.work"] - xs["a.work"] > 9e6
    procs = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"a.json", "b.json"}


def test_spill_and_readmit_carry_request_identity(tmp_path):
    """The spill payload carries the original request id; readmission
    books the serve.readmit join (flight + trace instant) against it —
    the hooks merge_traces renders as one cross-process timeline."""
    import numpy as np

    from nmfx.obs import flight
    from nmfx.serve import NMFXServer, ServeConfig, ServerClosed

    class _Eng:
        def compatibility_key(self, req):
            return None

    spill = str(tmp_path / "spill")
    a = np.abs(np.random.default_rng(0).normal(size=(8, 6))) + 0.1
    srv = NMFXServer(ServeConfig(spill_dir=spill), engine=_Eng(),
                     start=False)
    fut = srv.submit(a, ks=(2,), restarts=2)
    origin_id = fut.stats.request_id
    srv.close(cancel_pending=True)
    with pytest.raises(ServerClosed, match="spilled"):
        fut.result(timeout=30)
    names = [n for n in os.listdir(spill) if n.startswith("spill_")]
    assert len(names) == 1
    with np.load(os.path.join(spill, names[0]),
                 allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
    assert meta["request_id"] == origin_id
    assert meta["spill_pid"] == os.getpid()
    spill_evs = flight.default_recorder().events("serve.spill")
    assert spill_evs and spill_evs[-1]["request_id"] == origin_id
    # readmission books the join against the spilled identity
    srv2 = NMFXServer(ServeConfig(), engine=_Eng(), start=False)
    futs = srv2.readmit(spill_dir=spill)
    assert len(futs) == 1
    evs = flight.default_recorder().events("serve.readmit")
    assert evs[-1]["origin_request_id"] == origin_id
    assert evs[-1]["request_id"] == futs[0].stats.request_id
    srv2.close(cancel_pending=True)


# ---------------------------------------------------------------------
# nmfx-top
# ---------------------------------------------------------------------

def test_top_renders_text_and_html(tmp_path):
    for i in range(2):
        _publish(tmp_path, f"replica-{i}",
                 _registry_with(i, obs=[0.01 * (j + 1)
                                        for j in range(20)]))
    col = aggregate.FleetCollector(str(tmp_path), stale_after_s=600.0)
    eng = slo.SLOEngine(snapshot_fn=col.fleet_snapshot)
    frame = top.gather(col, eng)
    text = top.render_text(frame, str(tmp_path))
    assert "replica-0" in text and "replica-1" in text
    assert "live" in text
    assert "slo availability" in text and "· ok" in text
    assert "p50=" in text
    html_out = top.render_html(frame, str(tmp_path))
    assert "replica-1" in html_out and "fleet dashboard" in html_out
    # the CLI surface: --once prints, --html writes the static render
    out_html = tmp_path / "fleet.html"
    rc = top.main([str(tmp_path), "--html", str(out_html),
                   "--stale-after", "600"])
    assert rc == 0
    assert "replica-0" in out_html.read_text()


def test_top_empty_dir_reports_no_instances(tmp_path, capsys):
    rc = top.main([str(tmp_path), "--once"])
    assert rc == 0
    assert "no telemetry instances" in capsys.readouterr().out


# ---------------------------------------------------------------------
# true multi-process publishing (OS-process publishers, one ledger)
# ---------------------------------------------------------------------

_CHILD = """
import sys
from nmfx.obs import export, metrics

tdir, idx, series = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
reg = metrics.MetricsRegistry()
c = reg.counter("nmfx_serve_dispatches_total", "d", ("packed",))
for s in range(series):
    c.inc(idx + s + 1, packed=str(s))
h = reg.histogram("nmfx_serve_solve_seconds", "s")
for i in range(30):
    h.observe(0.003 * (i + 1) * (idx + 1))
export.TelemetryPublisher(tdir, instance=f"child-{idx}", role="bench",
                          registry=reg).publish_once()
"""


def _run_children(tmp_path, n_children, n_series):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(tmp_path), str(i),
         str(n_series)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env) for i in range(n_children)]
    errs = []
    for p in procs:
        try:
            _, e = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            _, e = p.communicate()
        if p.returncode != 0:
            errs.append(e[-3000:])
    assert not errs, errs


def _assert_exact_merge(tmp_path, n_children, n_series):
    col = aggregate.FleetCollector(str(tmp_path), stale_after_s=600.0)
    snap = col.fleet_snapshot()
    c = snap["nmfx_serve_dispatches_total"]["series"]
    for s in range(n_series):
        assert c[(str(s),)] == sum(i + s + 1
                                   for i in range(n_children)), s
    union = metrics.MetricsRegistry().histogram("u_seconds", "")
    for i in range(n_children):
        for j in range(30):
            union.observe(0.003 * (j + 1) * (i + 1))
    st = snap["nmfx_serve_solve_seconds"]["series"][()]
    assert st["count"] == n_children * 30
    assert st["bucket_counts"] == union.series()[()]["bucket_counts"]
    for q in (0.5, 0.95, 0.99):
        assert col.quantile("nmfx_serve_solve_seconds", q,
                            snapshot=snap) == union.quantile(q), q


def test_two_process_publishers_merge_exactly(tmp_path):
    """Two OS-process publishers x 2 labeled series: fleet counters
    equal the per-instance sums EXACTLY, histogram bucket counts and
    quantiles equal the union."""
    _run_children(tmp_path, n_children=2, n_series=2)
    _assert_exact_merge(tmp_path, 2, 2)


@pytest.mark.slow
def test_three_process_publishers_many_series_merge_exactly(tmp_path):
    """The heavier rung: 3 processes x 5 labeled series."""
    _run_children(tmp_path, n_children=3, n_series=5)
    _assert_exact_merge(tmp_path, 3, 5)
