"""Unified observability subsystem (ISSUE 10): structured tracer
round-trip, metrics-registry exactness under concurrent writers,
Prometheus exposition, flight-recorder ring/redaction/dump, and the
fault-site / degradation event plumbing.

Everything here is host-only (no device dispatch, no compiles) — the
serve-path trace acceptance test lives in tests/test_serve.py where it
shares that module's compiled executables, and the crash-dump chaos
test in tests/test_faults.py next to its watchdog siblings."""

import json
import threading

import pytest

from nmfx import faults
from nmfx.obs import flight, metrics, trace
from nmfx.obs.trace import Tracer


@pytest.fixture(autouse=True)
def _pristine_faults():
    faults.disarm()
    faults._reset_warned()
    yield
    faults.disarm()
    faults._reset_warned()


# ---------------------------------------------------------------------
# tracer: recording, export round-trip, per-thread nesting
# ---------------------------------------------------------------------

def _x_events_by_tid(chrome: dict) -> dict:
    out: dict = {}
    for ev in chrome["traceEvents"]:
        if ev.get("ph") == "X":
            out.setdefault(ev["tid"], []).append(ev)
    return out


def _assert_properly_nested(events: list) -> None:
    """On one thread, complete events must form a forest: any two
    intervals are either disjoint or one contains the other (that is
    what renders as a flame in Perfetto)."""
    stack = []
    for ev in sorted(events, key=lambda e: (e["ts"], -e["dur"])):
        start, end = ev["ts"], ev["ts"] + ev["dur"]
        while stack and start >= stack[-1] - 1e-6:
            stack.pop()
        if stack:
            assert end <= stack[-1] + 1e-6, \
                f"span {ev['name']} overlaps its sibling/parent"
        stack.append(end)


def test_trace_export_round_trip_nested_per_thread(tmp_path):
    """ISSUE 10 satellite: N threads of nested spans export as VALID
    Chrome trace JSON with per-thread proper nesting and thread-name
    metadata."""
    tr = Tracer()
    tr.enabled = True
    n_threads, m = 4, 25
    # all workers alive at once: thread idents are reused once a
    # thread exits, which would merge two workers onto one trace track
    barrier = threading.Barrier(n_threads)

    def work(i):
        import time

        barrier.wait()
        for j in range(m):
            with tr.span("outer", args={"i": i, "j": j}):
                with tr.span("inner"):
                    pass
                # retroactive span sized INSIDE the post-inner gap: a
                # fixed duration could back-compute a start before the
                # parent opened (or inside the inner sibling)
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < 2e-6:
                    pass
                tr.complete("retro", (time.perf_counter() - t0) / 2)

    threads = [threading.Thread(target=work, args=(i,),
                                name=f"obs-w{i}")
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    path = tmp_path / "trace.json"
    tr.export(str(path))
    chrome = json.loads(path.read_text())  # valid JSON round trip
    by_tid = _x_events_by_tid(chrome)
    assert len(by_tid) == n_threads
    meta = {ev["tid"]: ev["args"]["name"]
            for ev in chrome["traceEvents"] if ev.get("ph") == "M"}
    for tid, events in by_tid.items():
        assert meta[tid].startswith("obs-w")
        names = [e["name"] for e in events]
        assert names.count("outer") == m
        assert names.count("inner") == m
        assert names.count("retro") == m
        _assert_properly_nested(events)
        # every inner/retro interval is contained in SOME outer span
        outers = [(e["ts"], e["ts"] + e["dur"]) for e in events
                  if e["name"] == "outer"]
        for e in events:
            if e["name"] == "outer":
                continue
            assert any(lo - 1e-6 <= e["ts"]
                       and e["ts"] + e["dur"] <= hi + 1e-6
                       for lo, hi in outers), \
                f"{e['name']} not contained in any outer span"


def test_tracer_disabled_records_nothing():
    tr = Tracer()
    with tr.span("a"):
        pass
    tr.complete("b", 0.1)
    tr.instant("c")
    assert tr.event_count() == 0


def test_tracer_ring_bound_drops_oldest():
    tr = Tracer(max_events=10)
    tr.enabled = True
    for i in range(25):
        tr.complete(f"s{i}", 1e-6)
    assert tr.event_count() == 10
    assert tr.dropped == 15
    names = [e["name"] for e in tr.events()]
    assert names == [f"s{i}" for i in range(15, 25)]  # oldest dropped


def test_traced_decorator():
    tr = trace.default_tracer()
    tr.clear()
    calls = []

    @trace.traced
    def plain(x):
        calls.append(x)
        return x + 1

    @trace.traced("custom.name")
    def named():
        return 7

    assert plain(1) == 2 and named() == 7  # disabled: passthrough
    assert tr.event_count() == 0
    trace.enable()
    try:
        assert plain(2) == 3 and named() == 7
    finally:
        trace.disable()
    names = {e["name"] for e in tr.events()}
    assert "custom.name" in names
    assert any(n.endswith("plain") for n in names)
    tr.clear()


def test_profiler_phases_become_tracer_spans():
    """The Profiler is a view over the tracer: phases, marks, and
    worker-style add_seconds land on the process tracer's timeline —
    and the NullProfiler keeps the emission (the serving default) while
    staying a no-op for the books."""
    from nmfx.profiling import NullProfiler, Profiler

    tr = trace.default_tracer()
    tr.clear()
    trace.enable()
    try:
        prof = Profiler()
        with prof.phase("real.phase"):
            pass
        prof.mark("real.mark")
        prof.add_seconds("post.worker", 0.005)
        null = NullProfiler()
        with null.phase("null.phase"):
            pass
        null.add_seconds("null.retro", 0.003)
        null.mark("null.mark")
    finally:
        trace.disable()
    events = tr.events()
    names = {e["name"] for e in events}
    assert {"real.phase", "real.mark", "post.worker", "null.phase",
            "null.retro", "null.mark"} <= names
    by_name = {e["name"]: e for e in events}
    assert by_name["real.phase"]["ph"] == "X"
    assert by_name["real.mark"]["ph"] == "i"
    assert by_name["null.retro"]["ph"] == "X"
    assert by_name["null.retro"]["dur"] == pytest.approx(3000, rel=1e-6)
    # the books stayed no-op on the null profiler
    assert null.phases == {}
    assert prof.phases["real.phase"].count == 1
    tr.clear()


# ---------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------

def test_concurrent_writers_exact_counts():
    """ISSUE 10 satellite: N threads x M increments across S labeled
    series of one counter (plus a histogram) — the final counts are
    EXACT, not approximate (single-lock registry)."""
    c = metrics.counter("test_stress_total", "stress", ("series",))
    h = metrics.histogram("test_stress_seconds", "stress", ("series",))
    n_threads, m, n_series = 8, 250, 4

    def work(i):
        for j in range(m):
            s = str((i + j) % n_series)
            c.inc(series=s)
            h.observe(0.01 * ((i + j) % 3), series=s)

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.total() == n_threads * m
    total_obs = sum(
        st["count"]
        for st in h.series().values())
    assert total_obs == n_threads * m
    # per-series exactness: each (i+j) % n_series bucket got an equal
    # share (m and n_series chosen so the shares are uniform)
    for s in range(n_series):
        assert c.value(series=str(s)) == n_threads * m // n_series


def test_counter_is_monotonic_and_label_checked():
    c = metrics.counter("test_mono_total", "", ("a",))
    with pytest.raises(ValueError):
        c.inc(-1, a="x")
    with pytest.raises(ValueError):
        c.inc(wrong="x")
    with pytest.raises(ValueError):  # type conflict on redeclare
        metrics.gauge("test_mono_total")
    with pytest.raises(ValueError):  # label conflict on redeclare
        metrics.counter("test_mono_total", "", ("b",))
    assert metrics.counter("test_mono_total", "", ("a",)) is c


def test_histogram_quantiles_and_extremes():
    h = metrics.histogram("test_quant_seconds", "")
    for v in [0.002, 0.004, 0.008, 0.02, 0.04, 0.08, 0.2, 0.4, 0.8,
              2.0]:
        h.observe(v)
    st = h.series()[()]
    assert st["count"] == 10
    assert st["min"] == 0.002 and st["max"] == 2.0
    assert h.quantile(0.0) == 0.002
    assert h.quantile(1.0) == 2.0
    p50 = h.quantile(0.5)
    assert 0.01 <= p50 <= 0.1  # bucket-interpolated, bracketing the
    assert h.quantile(0.99) <= 2.0  # true median of 0.03


def test_snapshot_delta_windowing():
    c = metrics.counter("test_delta_total", "", ("lab",))
    g = metrics.gauge("test_delta_gauge", "")
    h = metrics.histogram("test_delta_seconds", "")
    c.inc(3, lab="a")
    g.set(5)
    h.observe(0.1)
    snap = metrics.registry().snapshot()
    c.inc(2, lab="a")
    c.inc(1, lab="b")
    g.set(9)
    h.observe(0.2)
    h.observe(0.3)
    d = metrics.registry().delta(snap)
    assert d["test_delta_total"]["series"][("a",)] == 2
    assert d["test_delta_total"]["series"][("b",)] == 1
    assert d["test_delta_gauge"]["series"][()] == 9  # gauge = level
    hd = d["test_delta_seconds"]["series"][()]
    assert hd["count"] == 2
    assert hd["sum"] == pytest.approx(0.5)


def test_prometheus_text_exposition():
    c = metrics.counter("test_promtext_total", "a counter", ("lab",))
    c.inc(2, lab="x")
    h = metrics.histogram("test_promtext_seconds", "a histogram",
                          buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = metrics.registry().prometheus_text()
    assert '# TYPE test_promtext_total counter' in text
    assert 'test_promtext_total{lab="x"} 2' in text
    assert '# TYPE test_promtext_seconds histogram' in text
    # cumulative buckets: 1 <= 0.1, 2 <= 1.0, 3 <= +Inf
    assert 'test_promtext_seconds_bucket{le="0.1"} 1' in text
    assert 'test_promtext_seconds_bucket{le="1.0"} 2' in text
    assert 'test_promtext_seconds_bucket{le="+Inf"} 3' in text
    assert 'test_promtext_seconds_count 3' in text
    assert 'test_promtext_seconds_sum' in text


def test_shim_counters_are_registry_backed():
    """The back-compat shims (exec_cache/data_cache/serve/checkpoint
    module counters) read the SAME registry series the Prometheus
    exposition exports — one source of truth."""
    from nmfx import checkpoint, data_cache, exec_cache, serve

    reg = metrics.registry()
    pairs = [
        (exec_cache.compile_count, "nmfx_exec_compile_total"),
        (data_cache.transfer_count, "nmfx_data_h2d_transfers_total"),
        (data_cache.h2d_bytes, "nmfx_data_h2d_bytes_total"),
        (serve.dispatch_count, "nmfx_serve_dispatches_total"),
        (checkpoint.chunks_solved_count, "nmfx_ckpt_chunks_solved_total"),
        (checkpoint.chunks_loaded_count, "nmfx_ckpt_chunks_loaded_total"),
    ]
    for shim, name in pairs:
        m = reg.get(name)
        assert m is not None, name
        assert shim() == int(sum(m.series().values())), name


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------

def test_flight_ring_bounded_and_redacted():
    rec = flight.FlightRecorder(max_events=8)
    rec.record("cat.small", x=1, ok=True)
    rec.record("cat.big", blob="z" * 10_000,
               **{f"k{i}": i for i in range(40)})
    evs = rec.events()
    big = next(e for e in evs if e["category"] == "cat.big")
    assert len(big["blob"]) < 300 and "…" in big["blob"]
    assert big["redacted_keys"] > 0
    for i in range(20):
        rec.record("cat.flood", i=i)
    assert len(rec.events()) == 8
    assert rec.dropped > 0


def test_flight_dump_writes_only_when_configured(tmp_path):
    rec = flight.FlightRecorder()
    rec.record("ev.one", detail="x")
    assert rec.dump("no-dir") is None  # never litters the cwd
    assert rec.last_dump()["reason"] == "no-dir"
    rec.configure(str(tmp_path))
    path = rec.dump("unit test/reason", extra={"err": ValueError("b")})
    assert path is not None
    art = json.loads(open(path).read())
    assert art["reason"] == "unit test/reason"
    assert art["extra"]["err"] == "b"
    assert any(e["category"] == "ev.one" for e in art["events"])
    explicit = rec.dump("explicit", path=str(tmp_path / "here.json"))
    assert explicit == str(tmp_path / "here.json")


def test_fault_fire_lands_flight_event():
    """Every armed fault FIRE books the site's FAULT_EVENTS category —
    the mapping lint rule NMFX008 keeps total over faults.SITES."""
    rec = flight.default_recorder()
    before = len(rec.events("fault.compile.build"))
    with faults.scoped("compile.build", every=2):
        assert not faults.fire("compile.build")  # hit 1: no fire
        assert faults.fire("compile.build")      # hit 2: fires
    evs = rec.events("fault.compile.build")
    assert len(evs) == before + 1
    assert evs[-1]["site"] == "compile.build"
    assert evs[-1]["hit"] == 2
    # arming itself is also on the record (scoped re-arms count too)
    assert any(e["site"] == "compile.build"
               for e in rec.events("fault.armed"))


def test_warn_once_records_every_degradation():
    """The warning dedups per category; the flight record does NOT —
    a postmortem needs the full degradation sequence."""
    rec = flight.default_recorder()
    before = len(rec.events("degradation"))
    with pytest.warns(RuntimeWarning, match="first"):
        faults.warn_once("test-obs-cat", "first")
    faults.warn_once("test-obs-cat", "second (no warning)")
    evs = rec.events("degradation")
    assert len(evs) == before + 2
    assert evs[-1]["degradation"] == "test-obs-cat"
    assert evs[-1]["msg"].startswith("second")


def test_armed_sites_appear_in_dump(tmp_path):
    rec = flight.default_recorder()
    with faults.scoped("h2d.transfer", every=3):
        path = rec.dump("armed-check",
                        path=str(tmp_path / "dump.json"))
    art = json.loads(open(path).read())
    assert "h2d.transfer" in art["armed_fault_sites"]


# ---------------------------------------------------------------------
# server-side metrics surfaces (no dispatch — cheap)
# ---------------------------------------------------------------------

def test_server_stats_snapshot_windows_to_server_start():
    from nmfx.serve import NMFXServer, ServeConfig

    probe = metrics.counter("test_server_window_total", "")
    probe.inc(5)  # before the server exists: outside its window
    srv = NMFXServer(ServeConfig(), engine=object(), start=False)
    probe.inc(2)
    d = srv.stats_snapshot()
    assert d["test_server_window_total"]["series"][()] == 2
    text = srv.metrics_text()
    assert "nmfx_serve" in text or "test_server_window_total" in text
    srv.close()
