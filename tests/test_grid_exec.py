"""Whole-grid execution (nmfx.ops.grid_mu + sweep grid_exec).

The grid path must be a drop-in for the sequential per-k path: same
per-(seed, k, restart) factorizations (bit-equal decisions, float-tolerance
factors — the dense-batched and packed layouts order GEMM reductions
differently), same consensus matrices, same best-restart selection — while
solving every rank in ONE compile, the reference's whole-grid-concurrent
job-array model (reference nmf.r:64-68, shuffled chunks nmf.r:111).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.grid_mu import mu_grid
from nmfx.ops.packed_mu import mu_packed, unpack_w
from nmfx.sweep import RESTART_AXIS, default_mesh, grid_exec_ok, sweep

KS = (2, 3, 4)
R = 5


@pytest.fixture(scope="module")
def data():
    return grouped_matrix(200, (10, 10, 10), effect=2.0, seed=0)


def _dense_init(a, root, ks, restarts, k_max, icfg=InitConfig()):
    w0l, h0l = [], []
    for k in ks:
        keys = jax.random.split(jax.random.fold_in(root, k), restarts)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, icfg, jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
    return jnp.concatenate(w0l), jnp.concatenate(h0l)


def test_mu_grid_matches_per_rank_packed(data):
    """Every lane of the grid solve reproduces the per-rank packed solve:
    identical stopping decisions, float-tolerance factors, and exactly-zero
    padding (the dense layout's correctness invariant)."""
    a = jnp.asarray(data, jnp.float32)
    cfg = SolverConfig(max_iter=600)
    root = jax.random.key(123)
    k_max = max(KS)
    w0, h0 = _dense_init(a, root, KS, R, k_max)
    # exact per-lane ranks — the direct-driver idiom (pad_live_mask)
    res = mu_grid(a, w0, h0, cfg,
                  job_ks=tuple(k for k in KS for _ in range(R)))
    for g, k in enumerate(KS):
        keys = jax.random.split(jax.random.fold_in(root, k), R)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        ref = mu_packed(a, w0s, h0s, cfg)
        sl = slice(g * R, (g + 1) * R)
        np.testing.assert_array_equal(np.asarray(ref.iterations),
                                      np.asarray(res.iterations[sl]))
        np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                      np.asarray(res.stop_reason[sl]))
        np.testing.assert_allclose(np.asarray(ref.dnorm),
                                   np.asarray(res.dnorm[sl]), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(unpack_w(ref.wp, R)),
                                   np.asarray(res.w[sl, :, :k]),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(
            np.asarray(ref.hp).reshape(R, k, -1),
            np.asarray(res.h[sl, :k, :]), rtol=2e-4, atol=2e-5)
        # padding must be EXACT zeros — the invariance the whole layout
        # rests on (a nonzero leak would bleed into Grams and labels)
        assert np.all(np.asarray(res.w[sl, :, k:]) == 0)
        assert np.all(np.asarray(res.h[sl, k:, :]) == 0)


def _assert_outputs_match(g, p, ks, keep_factors=False):
    for k in ks:
        np.testing.assert_array_equal(np.asarray(g[k].iterations),
                                      np.asarray(p[k].iterations))
        np.testing.assert_array_equal(np.asarray(g[k].stop_reasons),
                                      np.asarray(p[k].stop_reasons))
        np.testing.assert_array_equal(np.asarray(g[k].labels),
                                      np.asarray(p[k].labels))
        np.testing.assert_allclose(np.asarray(g[k].consensus),
                                   np.asarray(p[k].consensus), atol=1e-6)
        np.testing.assert_allclose(np.asarray(g[k].dnorms),
                                   np.asarray(p[k].dnorms), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(g[k].best_w),
                                   np.asarray(p[k].best_w),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(g[k].best_h),
                                   np.asarray(p[k].best_h),
                                   rtol=2e-4, atol=2e-5)
        if keep_factors:
            np.testing.assert_allclose(np.asarray(g[k].all_w),
                                       np.asarray(p[k].all_w),
                                       rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("use_mesh,backend", [(False, "auto"),
                                              (True, "auto"),
                                              (True, "pallas")])
def test_sweep_grid_matches_per_k(data, use_mesh, backend):
    """sweep(grid_exec='grid') ≡ sweep(grid_exec='per_k') on one device and
    on the restart mesh (restarts=5 on 8 devices exercises the padding
    lanes); the pallas scheduler composes with the mesh (per-device pools
    inside shard_map, interpret mode on CPU)."""
    mesh = default_mesh() if use_mesh else None
    if use_mesh:
        assert mesh is not None and RESTART_AXIS in mesh.axis_names
    # check_block pinned to 1: this test pins grid-vs-per_k COMPOSITION
    # (labels exactly equal), orthogonal to the cadence drift class the
    # pallas default N=4 carries (tests/test_check_block.py owns that)
    scfg = SolverConfig(max_iter=600, backend=backend, check_block=1)
    g = sweep(data, ConsensusConfig(ks=KS, restarts=R, grid_exec="grid"),
              scfg, InitConfig(), mesh)
    p = sweep(data, ConsensusConfig(ks=KS, restarts=R, grid_exec="per_k"),
              SolverConfig(max_iter=600), InitConfig(), mesh)
    _assert_outputs_match(g, p, KS)


@pytest.mark.slow
def test_sweep_grid_keep_factors_and_argmin(data):
    """keep_factors retention and the argmin label rule both flow through
    the grid path; argmin labels must come from the true rows only (the
    zero-padded rows would otherwise always win the argmin)."""
    scfg = SolverConfig(max_iter=400)
    cc = dict(ks=KS, restarts=3, label_rule="argmin", keep_factors=True)
    g = sweep(data, ConsensusConfig(grid_exec="grid", **cc), scfg,
              InitConfig())
    p = sweep(data, ConsensusConfig(grid_exec="per_k", **cc), scfg,
              InitConfig())
    _assert_outputs_match(g, p, KS, keep_factors=True)
    for k in KS:
        assert np.asarray(g[k].labels).max() < k
        assert np.asarray(g[k].all_w).shape == (3, data.shape[0], k)


def test_grid_exec_auto_and_validation(data):
    """auto → grid only for eligible configs; grid_exec='grid' on an
    ineligible config is a clear error, and auto falls back silently."""
    assert grid_exec_ok(SolverConfig(), None)
    assert grid_exec_ok(SolverConfig(algorithm="hals"), None)
    assert not grid_exec_ok(SolverConfig(algorithm="kl"), None)
    assert not grid_exec_ok(SolverConfig(backend="vmap"), None)
    with pytest.raises(ValueError, match="grid_exec='grid'"):
        sweep(data, ConsensusConfig(ks=KS, restarts=2, grid_exec="grid"),
              SolverConfig(algorithm="kl", max_iter=50), InitConfig())
    # auto + ineligible solver: per-k fallback, no error
    out = sweep(data, ConsensusConfig(ks=(2, 3), restarts=2),
                SolverConfig(algorithm="neals", max_iter=50), InitConfig())
    assert set(out) == {2, 3}
    with pytest.raises(ValueError, match="grid_exec"):
        ConsensusConfig(grid_exec="bogus")


def test_hals_backend_fingerprints_differ(data):
    """hals' vmap and packed executions are not bit-identical, so they
    must not share a checkpoint fingerprint (the registry's resolved-
    backend contract)."""
    from nmfx.registry import _fingerprint

    a = np.asarray(data, np.float32)
    fp = {b: _fingerprint(a, SolverConfig(algorithm="hals", backend=b),
                          InitConfig(), 3, 123, "argmax")
          for b in ("vmap", "packed", "auto")}
    assert fp["vmap"] != fp["packed"]
    # auto resolves hals to the packed/scheduled family on every sweep
    # path (per-k included), so it shares the explicit-packed fingerprint
    assert fp["auto"] == fp["packed"]


@pytest.mark.slow
def test_hals_grid_matches_per_k_vmap(data):
    """hals through the whole-grid scheduler (and the per-k packed backend)
    reproduces the vmapped generic driver: same stop decisions, factors to
    float tolerance — the VERDICT r2 #3 'packed backend for hals'."""
    scfg_v = SolverConfig(algorithm="hals", backend="vmap", max_iter=400)
    scfg_g = SolverConfig(algorithm="hals", backend="packed", max_iter=400)
    cc = dict(ks=KS, restarts=3)
    p = sweep(data, ConsensusConfig(grid_exec="per_k", **cc), scfg_v,
              InitConfig())
    g = sweep(data, ConsensusConfig(grid_exec="grid", **cc), scfg_g,
              InitConfig())
    _assert_outputs_match(g, p, KS)
    # per-k packed backend (single-rank route through the scheduler)
    solo_v = sweep(data, ConsensusConfig(ks=(3,), restarts=3,
                                         grid_exec="per_k"), scfg_v,
                   InitConfig())
    solo_p = sweep(data, ConsensusConfig(ks=(3,), restarts=3,
                                         grid_exec="per_k"), scfg_g,
                   InitConfig())
    _assert_outputs_match(solo_p, solo_v, (3,))


@pytest.mark.parametrize("algorithm", ["neals", "als", "snmf", "kl"])
@pytest.mark.slow
def test_gram_family_grid_matches_per_k_vmap(data, algorithm):
    """neals/als/snmf/kl through the whole-grid scheduler (explicit
    backend='packed' opt-in; als joined in round 5 — its min-norm lstsq
    half-steps batch like neals' Gram solves) reproduce the vmapped generic
    driver: same stop decisions and labels, factors to float tolerance.
    Their 'auto' default stays the vmap family — the grid engine exists
    for its compile-time win (one jit for the whole sweep vs one per
    rank; for kl the slot count additionally bounds the (B, m, n)
    quotient working set), so this parity is what makes the opt-in
    safe."""
    scfg_v = SolverConfig(algorithm=algorithm, backend="vmap", max_iter=400)
    scfg_g = SolverConfig(algorithm=algorithm, backend="packed",
                          max_iter=400)
    cc = dict(ks=KS, restarts=3)
    p = sweep(data, ConsensusConfig(grid_exec="per_k", **cc), scfg_v,
              InitConfig())
    g = sweep(data, ConsensusConfig(grid_exec="grid", **cc), scfg_g,
              InitConfig())
    for k in KS:
        np.testing.assert_array_equal(np.asarray(g[k].iterations),
                                      np.asarray(p[k].iterations))
        np.testing.assert_array_equal(np.asarray(g[k].stop_reasons),
                                      np.asarray(p[k].stop_reasons))
        np.testing.assert_array_equal(np.asarray(g[k].labels),
                                      np.asarray(p[k].labels))
        np.testing.assert_allclose(np.asarray(g[k].consensus),
                                   np.asarray(p[k].consensus), atol=1e-6)
        np.testing.assert_allclose(np.asarray(g[k].dnorms),
                                   np.asarray(p[k].dnorms), rtol=1e-4)
        # factor tolerance is slightly wider than _assert_outputs_match's:
        # the batched Gram solve's Tikhonov jitter uses trace/k_max vs the
        # per-restart trace/k (see grid_mu._batched_gram_solve), a
        # ~10·eps-scale perturbation the iteration amplifies into ~3e-5
        # absolute drift on near-zero factor entries. snmf gets a wider
        # band still: at k above the planted 3-group structure it
        # actively kills surplus components, and a dying component's
        # near-zero trajectory amplifies the same perturbation ~50x
        # (measured 1.9e-3 abs at k=4 on this fixture) while every
        # stable observable above — stops, labels, consensus, dnorms —
        # stays pinned tight
        f_rtol, f_atol = ((5e-3, 1e-3) if algorithm == "snmf"
                          else (2e-4, 1e-4))
        np.testing.assert_allclose(np.asarray(g[k].best_w),
                                   np.asarray(p[k].best_w),
                                   rtol=f_rtol, atol=f_atol)
        np.testing.assert_allclose(np.asarray(g[k].best_h),
                                   np.asarray(p[k].best_h),
                                   rtol=f_rtol, atol=f_atol)
    # the per-k route (single-rank wrapper around the grid engine) —
    # reachable via backend='packed' with grid_exec='per_k' or a
    # single-k sweep
    solo_v = sweep(data, ConsensusConfig(ks=(3,), restarts=3,
                                         grid_exec="per_k"), scfg_v,
                   InitConfig())
    solo_p = sweep(data, ConsensusConfig(ks=(3,), restarts=3,
                                         grid_exec="per_k"), scfg_g,
                   InitConfig())
    np.testing.assert_array_equal(np.asarray(solo_p[3].iterations),
                                  np.asarray(solo_v[3].iterations))
    np.testing.assert_array_equal(np.asarray(solo_p[3].labels),
                                  np.asarray(solo_v[3].labels))
    np.testing.assert_allclose(np.asarray(solo_p[3].best_h),
                               np.asarray(solo_v[3].best_h),
                               rtol=2e-4, atol=1e-4)


def test_grid_resume_solves_only_missing_ranks(data, tmp_path):
    """Registry resume under grid execution: checkpointed ranks load, the
    missing ranks form one smaller grid solve, and the merged result
    matches a fresh full sweep."""
    from nmfx.registry import SweepRegistry

    scfg = SolverConfig(max_iter=400)
    icfg = InitConfig()
    full_cfg = ConsensusConfig(ks=KS, restarts=3, grid_exec="grid")
    part_cfg = ConsensusConfig(ks=KS[:2], restarts=3, grid_exec="grid")

    reg = SweepRegistry.open(str(tmp_path), np.asarray(data, np.float32),
                             scfg, icfg, 3, part_cfg.seed,
                             part_cfg.label_rule)
    first = sweep(data, part_cfg, scfg, icfg, registry=reg)
    reg2 = SweepRegistry.open(str(tmp_path), np.asarray(data, np.float32),
                              scfg, icfg, 3, full_cfg.seed,
                              full_cfg.label_rule)
    resumed = sweep(data, full_cfg, scfg, icfg, registry=reg2)
    fresh = sweep(data, full_cfg, scfg, icfg)
    for k in KS[:2]:  # loaded from checkpoint: bit-equal to the first run
        np.testing.assert_array_equal(np.asarray(resumed[k].consensus),
                                      np.asarray(first[k].consensus))
    # the remaining rank was solved (alone → per-k path is fine too) and
    # matches the fresh run's decisions
    np.testing.assert_array_equal(np.asarray(resumed[KS[2]].iterations),
                                  np.asarray(fresh[KS[2]].iterations))
    np.testing.assert_allclose(np.asarray(resumed[KS[2]].consensus),
                               np.asarray(fresh[KS[2]].consensus),
                               atol=1e-6)


@pytest.mark.slow
def test_snmf_dead_component_parity():
    """snmf engines agree even when W columns genuinely DIE mid-solve —
    the case sparse NMF actively encourages at k above the data's
    structure (VERDICT r4 Weak #6 / ADVICE r4). The grid block masks the
    beta L1 coupling by PADDING (each lane's true-k columns, from the
    initial factors), not by nonzero-W: a round-5 measurement of the
    nonzero-W mask showed the engines diverging to max|dC|=1.0 once
    components died (dead components dropped from the coupling change
    the LIVE components' solves), while the padding mask keeps the dead
    row in the k x k ones system exactly like the per-restart form
    (solvers/snmf.py)."""
    a = grouped_matrix(120, (10, 10), effect=2.0, seed=0)  # 2 real groups
    ks = (4, 5)  # above the structure: components die under sparsity
    found_death = False
    for beta in (0.5, 8.0):
        scfg_v = SolverConfig(algorithm="snmf", backend="vmap",
                              max_iter=400, sparsity_beta=beta)
        scfg_g = SolverConfig(algorithm="snmf", backend="packed",
                              max_iter=400, sparsity_beta=beta)
        cc = dict(ks=ks, restarts=4)
        v = sweep(a, ConsensusConfig(grid_exec="per_k", keep_factors=True,
                                     **cc), scfg_v, InitConfig())
        g = sweep(a, ConsensusConfig(grid_exec="grid", keep_factors=True,
                                     **cc), scfg_g, InitConfig())
        for k in ks:
            wv = np.asarray(v[k].all_w)
            wg = np.asarray(g[k].all_w)
            dead_v = int((np.abs(wv).sum(axis=1) == 0).sum())
            dead_g = int((np.abs(wg).sum(axis=1) == 0).sum())
            # engines must kill the SAME components...
            assert dead_v == dead_g, (beta, k, dead_v, dead_g)
            found_death = found_death or dead_v > 0
            # ...and produce the same consensus and stop decisions
            np.testing.assert_allclose(np.asarray(g[k].consensus),
                                       np.asarray(v[k].consensus),
                                       atol=1e-6)
            np.testing.assert_array_equal(np.asarray(g[k].labels),
                                          np.asarray(v[k].labels))
            dit = np.abs(np.asarray(g[k].iterations)
                         - np.asarray(v[k].iterations))
            # float-tolerance trajectory drift may move a stop by one
            # check (2 iterations); anything more is semantic divergence
            assert dit.max() <= 2, (beta, k, dit)
    # the fixture must actually exercise the divergence-prone case
    assert found_death, "no component ever died; fixture too easy"
