"""Per-restart factor retention, recompute-by-key, and the generic grid
reduction — parity with the reference's job registry + ``reduceGridBy``
(reference ``nmf.r:50, 72-98``): the registry keeps every job's full
``list(W, H, iter)`` and the reduction groups those results by a grid axis.
"""

import numpy as np
import pytest

import jax

from nmfx import (
    ConsensusConfig,
    InitConfig,
    SolverConfig,
    consensus_from_cells,
    grid_cells,
    nmfconsensus,
    reduce_grid,
    restart_factors,
)
from nmfx.api import ConsensusResult
from nmfx.sweep import grid_mesh, sweep, sweep_one_k

RESTARTS = 5
KS = (2, 3)


def _cfg(backend):
    return SolverConfig(algorithm="mu", max_iter=300, backend=backend)


def _sweep(a, k, backend, mesh=None, keep=True):
    key = jax.random.fold_in(jax.random.key(123), k)
    return sweep_one_k(a, key, k, RESTARTS, _cfg(backend), InitConfig(),
                       mesh=mesh, keep_factors=keep)


def test_split_prefix_stability():
    """The sweep pads the restart axis to the mesh size; restart r's key
    must not depend on the padding (restart_factors relies on this)."""
    key = jax.random.key(42)
    long = jax.random.split(key, 56)
    short = jax.random.split(key, 50)
    np.testing.assert_array_equal(
        jax.random.key_data(long[:50]), jax.random.key_data(short))


@pytest.mark.parametrize("backend", ["vmap", "packed"])
def test_keep_factors_match_solo_run(two_group_data, backend):
    """all_w[r]/all_h[r] reproduce a solo nmf() run with restart r's
    seed-derived key — the VERDICT acceptance test for retention."""
    out = _sweep(two_group_data, 2, backend)
    assert out.all_w.shape == (RESTARTS, two_group_data.shape[0], 2)
    assert out.all_h.shape == (RESTARTS, 2, two_group_data.shape[1])
    for r in (0, RESTARTS - 1):
        solo = restart_factors(two_group_data, 2, r, restarts=RESTARTS,
                               seed=123, solver_cfg=_cfg(backend))
        np.testing.assert_allclose(np.asarray(out.all_w[r]),
                                   np.asarray(solo.w), rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out.all_h[r]),
                                   np.asarray(solo.h), rtol=2e-4, atol=1e-5)
        assert int(out.iterations[r]) == int(solo.iterations)


@pytest.mark.parametrize("backend", ["vmap", "packed"])
def test_best_factors_are_the_lowest_residual_restart(two_group_data,
                                                      backend):
    out = _sweep(two_group_data, 3, backend)
    best = int(np.argmin(np.asarray(out.dnorms)))
    np.testing.assert_array_equal(np.asarray(out.best_w),
                                  np.asarray(out.all_w[best]))
    np.testing.assert_array_equal(np.asarray(out.best_h),
                                  np.asarray(out.all_h[best]))


@pytest.mark.parametrize("backend", ["vmap", "packed"])
def test_keep_factors_mesh_invariance(two_group_data, backend):
    """Retained factors agree with and without a restart mesh: labels and
    iteration counts exactly, factor values to f32 GEMM-blocking noise (the
    padded batch width differs between mesh shapes, so XLA tiles the
    reductions differently — measured max rel diff ~5e-5 over 300 iters)."""
    ref = _sweep(two_group_data, 2, backend, mesh=None)
    mesh = grid_mesh(8)
    got = _sweep(two_group_data, 2, backend, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(ref.labels),
                                  np.asarray(got.labels))
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_allclose(np.asarray(ref.all_w),
                               np.asarray(got.all_w), rtol=5e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ref.all_h),
                               np.asarray(got.all_h), rtol=5e-4, atol=1e-5)


def test_keep_factors_composes_with_restart_chunk(two_group_data):
    """Chunked execution (the bounded-memory path) must retain the same
    factors as the unchunked sweep — prefix-stable keys make chunking
    invisible."""
    key = jax.random.fold_in(jax.random.key(123), 2)
    cfg = SolverConfig(algorithm="mu", max_iter=200, backend="vmap")
    ref = sweep_one_k(two_group_data, key, 2, RESTARTS, cfg, InitConfig(),
                      keep_factors=True)
    chunked = SolverConfig(algorithm="mu", max_iter=200, backend="vmap",
                           restart_chunk=2)
    got = sweep_one_k(two_group_data, key, 2, RESTARTS, chunked,
                      InitConfig(), keep_factors=True)
    np.testing.assert_array_equal(np.asarray(ref.all_w),
                                  np.asarray(got.all_w))
    np.testing.assert_array_equal(np.asarray(ref.all_h),
                                  np.asarray(got.all_h))


def test_keep_factors_off_returns_none(two_group_data):
    out = _sweep(two_group_data, 2, "packed", keep=False)
    assert out.all_w is None and out.all_h is None
    with pytest.raises(ValueError, match="keep_factors=True"):
        grid_cells({2: out})


def test_keep_factors_grid_mesh_raises(two_group_data):
    mesh = grid_mesh(1, feature_shards=2)
    with pytest.raises(ValueError, match="feature/sample-sharded"):
        _sweep(two_group_data, 2, "packed", mesh=mesh)


def _full_sweep(a, keep=True):
    ccfg = ConsensusConfig(ks=KS, restarts=RESTARTS, seed=123,
                           keep_factors=keep)
    return sweep(a, ccfg, _cfg("packed"), InitConfig())


def test_reduce_grid_by_k_reproduces_consensus(two_group_data):
    """reduce_grid with the reference's own reduction (nmf.r:117) agrees
    with the on-device einsum consensus."""
    raw = _full_sweep(two_group_data)
    host = reduce_grid(raw, consensus_from_cells, by="k")
    assert sorted(host) == sorted(KS)
    for k in KS:
        np.testing.assert_allclose(host[k], np.asarray(raw[k].consensus),
                                   atol=1e-6)


def test_reduce_grid_by_restart(two_group_data):
    """The transpose grouping: one group per restart index, each holding
    every rank's cell for that restart (the reference's num.clusterings
    axis, nmf.r:64-68)."""
    raw = _full_sweep(two_group_data)
    got = reduce_grid(raw, lambda cells: [(c.k, c.restart) for c in cells],
                      by="restart")
    assert sorted(got) == list(range(RESTARTS))
    for r in range(RESTARTS):
        assert got[r] == [(k, r) for k in sorted(KS)]


def test_reduce_grid_custom_fun(two_group_data):
    """A reduction the hardcoded pipeline can't express: per-k mean W
    across restarts (restart-level stability analysis)."""
    raw = _full_sweep(two_group_data)
    mean_w = reduce_grid(
        raw, lambda cells: np.mean([c.w for c in cells], axis=0), by="k")
    for k in KS:
        assert mean_w[k].shape == (two_group_data.shape[0], k)
        np.testing.assert_allclose(
            mean_w[k], np.asarray(raw[k].all_w).mean(axis=0), rtol=1e-6)


def test_reduce_grid_default_fun_is_reference_reduction(two_group_data):
    raw = _full_sweep(two_group_data)
    got = reduce_grid(raw)  # fun defaults to consensus_from_cells
    want = reduce_grid(raw, consensus_from_cells)
    for k in KS:
        np.testing.assert_array_equal(got[k], want[k])


def test_result_load_fails_fast_on_missing_required_field(two_group_data,
                                                          tmp_path):
    """Only the optional factor fields may be absent from a saved result;
    a required field missing (version mismatch / corruption) must raise at
    load, not surface as None deep in later analysis."""
    res = nmfconsensus(two_group_data, ks=(2,), restarts=2,
                       solver_cfg=_cfg("packed"))
    path = str(tmp_path / "res.npz")
    res.save(path)
    with np.load(path) as z:
        arrays = {n: z[n] for n in z.files if n != "k2_consensus"}
    np.savez(path, **arrays)
    with pytest.raises(KeyError):
        ConsensusResult.load(path)


def test_reduce_grid_rejects_unknown_axis(two_group_data):
    raw = _full_sweep(two_group_data)
    with pytest.raises(ValueError, match="'k' or 'restart'"):
        reduce_grid(raw, consensus_from_cells, by="job")


def test_restart_factors_bounds():
    with pytest.raises(ValueError, match="outside"):
        restart_factors(np.ones((4, 4)), 2, 5, restarts=5)


def test_reduce_grid_accepts_consensus_result(two_group_data):
    """reduce_grid works directly on the high-level nmfconsensus result —
    the object a keep_factors user actually holds."""
    res = nmfconsensus(two_group_data, ks=KS, restarts=RESTARTS,
                       solver_cfg=_cfg("packed"), keep_factors=True)
    host = reduce_grid(res)  # default fun = reference consensus reduction
    for k in KS:
        np.testing.assert_allclose(host[k], res.per_k[k].consensus,
                                   atol=1e-6)
    # without retention the same call explains what to do
    res2 = nmfconsensus(two_group_data, ks=(2,), restarts=2,
                        solver_cfg=_cfg("packed"))
    with pytest.raises(ValueError, match="keep_factors=True"):
        reduce_grid(res2)


def test_nmfconsensus_keep_factors_and_save_roundtrip(two_group_data,
                                                      tmp_path):
    res = nmfconsensus(two_group_data, ks=KS, restarts=RESTARTS,
                       solver_cfg=_cfg("packed"), keep_factors=True)
    for k in KS:
        r = res.per_k[k]
        assert r.all_w.shape == (RESTARTS, two_group_data.shape[0], k)
        best = int(np.argmin(r.dnorms))
        np.testing.assert_array_equal(r.best_h, r.all_h[best])
    path = str(tmp_path / "res.npz")
    res.save(path)
    loaded = ConsensusResult.load(path)
    for k in KS:
        np.testing.assert_array_equal(loaded.per_k[k].all_w,
                                      res.per_k[k].all_w)

    # without retention the optional fields stay None through save/load
    res2 = nmfconsensus(two_group_data, ks=(2,), restarts=2,
                        solver_cfg=_cfg("packed"))
    assert res2.per_k[2].all_w is None
    path2 = str(tmp_path / "res2.npz")
    res2.save(path2)
    assert ConsensusResult.load(path2).per_k[2].all_w is None


def test_registry_roundtrip_with_factors(two_group_data, tmp_path):
    """Checkpointed keep_factors sweeps persist and resume the factor
    arrays; a registry written without factors refuses a keep_factors run
    (fingerprint mismatch) instead of silently serving factor-less
    results."""
    from nmfx.registry import SweepRegistry

    scfg = _cfg("packed")
    d = str(tmp_path / "reg")
    reg = SweepRegistry.open(d, two_group_data, scfg, InitConfig(),
                             RESTARTS, 123, "argmax", keep_factors=True)
    out = _sweep(two_group_data, 2, "packed")
    reg.save(2, out)
    loaded = reg.try_load(2)
    np.testing.assert_array_equal(loaded.all_w, np.asarray(out.all_w))
    np.testing.assert_array_equal(loaded.all_h, np.asarray(out.all_h))

    with pytest.raises(ValueError, match="different"):
        SweepRegistry.open(d, two_group_data, scfg, InitConfig(),
                           RESTARTS, 123, "argmax", keep_factors=False)
