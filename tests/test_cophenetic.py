"""Rank-selection layer vs scipy oracle (reference nmf.r:165-177)."""

import numpy as np
import pytest
import scipy.cluster.hierarchy as sch
import scipy.spatial.distance as ssd

from nmfx.cophenetic import (average_linkage_numpy as average_linkage,
                             condensed, cophenetic_rho,
                             cut_tree_numpy as cut_tree, rank_selection)


def _random_dist(n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    d = ssd.squareform(ssd.pdist(x))
    return d


@pytest.mark.parametrize("n,seed", [(6, 0), (12, 1), (25, 2), (40, 3)])
def test_linkage_matches_scipy(n, seed):
    d = _random_dist(n, seed)
    ours = average_linkage(d)
    theirs = sch.linkage(ssd.squareform(d), method="average")
    # heights and cluster sizes must agree merge-for-merge
    np.testing.assert_allclose(ours.linkage[:, 2], theirs[:, 2], rtol=1e-10)
    np.testing.assert_allclose(ours.linkage[:, 3], theirs[:, 3])
    # generic-position distances => identical merge pairs
    np.testing.assert_array_equal(np.sort(ours.linkage[:, :2], axis=1),
                                  np.sort(theirs[:, :2], axis=1))


@pytest.mark.parametrize("n,seed", [(10, 4), (30, 5)])
def test_cophenetic_matches_scipy(n, seed):
    d = _random_dist(n, seed)
    ours = average_linkage(d)
    z = sch.linkage(ssd.squareform(d), method="average")
    coph_scipy = sch.cophenet(z)
    np.testing.assert_allclose(condensed(ours.coph), coph_scipy, rtol=1e-10)
    # rho vs scipy's cophenet correlation output
    rho_scipy, _ = sch.cophenet(z, ssd.squareform(d))
    assert abs(cophenetic_rho(d, ours.coph) - rho_scipy) < 1e-10


@pytest.mark.parametrize("k", [1, 2, 3, 5])
def test_cut_tree_matches_scipy(k):
    d = _random_dist(18, 6)
    ours = average_linkage(d)
    labels = cut_tree(ours.linkage, 18, k)
    z = sch.linkage(ssd.squareform(d), method="average")
    theirs = sch.fcluster(z, t=k, criterion="maxclust")
    assert labels.min() == 1 and labels.max() == k
    # same partition up to label permutation
    for a in range(18):
        for b in range(18):
            assert (labels[a] == labels[b]) == (theirs[a] == theirs[b])


def test_leaf_order_is_permutation():
    d = _random_dist(15, 7)
    ours = average_linkage(d)
    assert sorted(ours.order.tolist()) == list(range(15))
    # dendrogram order must keep merged clusters contiguous at every height:
    # spot-check against scipy's leaves ordering semantics
    z = sch.linkage(ssd.squareform(d), method="average")
    scipy_leaves = sch.leaves_list(z)
    # both orders cluster the same pairs adjacently at the lowest merge
    a, b = int(z[0, 0]), int(z[0, 1])
    ia, ib = list(ours.order).index(a), list(ours.order).index(b)
    assert abs(ia - ib) == 1


def test_perfect_consensus_rho_is_one():
    # block-diagonal consensus: two clean clusters => rho == 1
    c = np.zeros((8, 8))
    c[:4, :4] = 1.0
    c[4:, 4:] = 1.0
    rho, membership, order = rank_selection(c, 2)
    assert rho == pytest.approx(1.0)
    assert len(set(membership[:4])) == 1
    assert len(set(membership[4:])) == 1
    assert membership[0] != membership[7]


# --- complete/single linkage (beyond the reference's average) --------------

@pytest.mark.parametrize("method,scipy_name", [("complete", "complete"),
                                               ("single", "single")])
def test_other_linkages_match_scipy(method, scipy_name):
    from scipy.cluster.hierarchy import cophenet, linkage as scipy_linkage
    from scipy.spatial.distance import squareform

    from nmfx.cophenetic import condensed, linkage_numpy

    rng = np.random.default_rng(8)
    n = 24
    x = rng.uniform(0, 1, (n, 5))
    dist = np.sqrt(((x[:, None] - x[None, :]) ** 2).sum(-1))
    np.fill_diagonal(dist, 0.0)
    hc = linkage_numpy(dist, method)
    z = scipy_linkage(squareform(dist, checks=False), method=scipy_name)
    np.testing.assert_allclose(hc.linkage[:, 2], z[:, 2], rtol=1e-10)
    coph_ref = cophenet(z)
    np.testing.assert_allclose(condensed(hc.coph), coph_ref, rtol=1e-10)


def test_linkage_validation():
    from nmfx.cophenetic import linkage_numpy

    with pytest.raises(ValueError, match="linkage"):
        linkage_numpy(np.zeros((3, 3)), "ward")
