"""Restart screening (``SolverConfig.screen`` — ISSUE 12): the cheap
sketched pass ranks the restart pool, exact iterations go only to the
top-``screen_keep`` survivors, and three contracts hold:

* survivor-lane results are BIT-IDENTICAL to solo exact runs of those
  lanes (the acceptance criterion — init from the canonical key +
  ``solve``, compared bitwise);
* screened-out lanes behave exactly like pad lanes (labels -1,
  ``StopReason.SCREENED``, masked from the consensus reduction, never
  selected as best restart);
* the ``min_restarts`` floor counts screened lanes as non-survivors
  (typed ``InsufficientRestarts`` below it).

Smallest shapes only (<= 60x24, restarts <= 8) per the tier-1 budget.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.api import nmfconsensus
from nmfx.config import InitConfig, SolverConfig
from nmfx.datasets import two_group_matrix
from nmfx.faults import InsufficientRestarts
from nmfx.init import initialize
from nmfx.solvers.base import StopReason, solve
from nmfx.sweep import sweep_one_k

RESTARTS = 8
KEEP = 3


def small_matrix():
    return two_group_matrix(n_genes=60, n_per_group=12, seed=0)


def screened_cfg(**kw):
    base = dict(algorithm="mu", max_iter=200, screen=True,
                screen_keep=KEEP)
    base.update(kw)
    return SolverConfig(**base)


@pytest.fixture(scope="module")
def screened_out():
    a = small_matrix()
    key = jax.random.fold_in(jax.random.key(123), 2)
    out = sweep_one_k(a, key, 2, RESTARTS, screened_cfg(), InitConfig())
    return a, key, out


def test_config_validation():
    with pytest.raises(ValueError, match="screen_keep"):
        SolverConfig(screen=True)
    with pytest.raises(ValueError, match="vmapped"):
        SolverConfig(screen=True, screen_keep=2, backend="packed")
    with pytest.raises(ValueError, match="sketched screening"):
        SolverConfig(algorithm="als", screen=True, screen_keep=2)
    with pytest.raises(ValueError, match="screen_keep"):
        SolverConfig(screen_keep=0)
    # screen_keep > restarts is a sweep-time error (config doesn't
    # know the restart count)
    with pytest.raises(ValueError, match=r"screen_keep must be in"):
        sweep_one_k(small_matrix(), jax.random.key(0), 2, 4,
                    screened_cfg(screen_keep=9), InitConfig())


def test_exactly_keep_survivors(screened_out):
    _, _, out = screened_out
    stops = np.asarray(out.stop_reasons)
    surv = stops != int(StopReason.SCREENED)
    assert int(surv.sum()) == KEEP
    # screened lanes record the screening budget spent, -1 labels, inf
    # dnorm — the pad-lane shape
    labels = np.asarray(out.labels)
    dn = np.asarray(out.dnorms)
    iters = np.asarray(out.iterations)
    cfg = screened_cfg()
    for i in np.nonzero(~surv)[0]:
        assert np.all(labels[i] == -1)
        assert np.isinf(dn[i])
        assert iters[i] == cfg.sketch.screen_iters


def test_survivors_bit_identical_to_solo_exact_runs(screened_out):
    """THE acceptance criterion: each survivor lane's results equal a
    SOLO exact run of that lane — same canonical key, plain
    ``initialize`` + ``solve`` — bit for bit."""
    a, key, out = screened_out
    stops = np.asarray(out.stop_reasons)
    surv = np.nonzero(stops != int(StopReason.SCREENED))[0]
    keys = jax.random.split(key, RESTARTS)
    exact = SolverConfig(algorithm="mu", max_iter=200)
    aj = jnp.asarray(a, jnp.float32)
    for i in surv:
        w0, h0 = initialize(keys[i], aj, 2, InitConfig(), jnp.float32)
        r = solve(a, w0, h0, exact)
        assert np.asarray(r.dnorm).tobytes() == \
            np.asarray(out.dnorms)[i].tobytes()
        assert int(r.iterations) == int(np.asarray(out.iterations)[i])
        assert int(r.stop_reason) == int(stops[i])
        solo_labels = np.asarray(jnp.argmax(r.h, axis=0))
        assert np.array_equal(solo_labels, np.asarray(out.labels)[i])
        # and the best-restart factors come verbatim from a survivor
    best = surv[np.argmin(np.asarray(out.dnorms)[surv])]
    w0, h0 = initialize(keys[best], aj, 2, InitConfig(), jnp.float32)
    r = solve(a, w0, h0, exact)
    assert np.asarray(r.w).tobytes() == np.asarray(out.best_w).tobytes()
    assert np.asarray(r.h).tobytes() == np.asarray(out.best_h).tobytes()


def test_masked_lanes_behave_like_pad_lanes(screened_out):
    """The consensus is the mean connectivity over SURVIVORS only —
    exactly the quarantine/pad reduction: recompute it from the
    survivor labels and compare."""
    _, _, out = screened_out
    stops = np.asarray(out.stop_reasons)
    surv = np.nonzero(stops != int(StopReason.SCREENED))[0]
    labels = np.asarray(out.labels)[surv]
    conn = (labels[:, :, None] == labels[:, None, :]).astype(np.float64)
    expected = conn.mean(axis=0)
    np.testing.assert_allclose(np.asarray(out.consensus, np.float64),
                               expected, atol=1e-6)


def test_screening_deterministic(screened_out):
    a, key, out = screened_out
    out2 = sweep_one_k(a, key, 2, RESTARTS, screened_cfg(),
                       InitConfig())
    assert np.array_equal(np.asarray(out.stop_reasons),
                          np.asarray(out2.stop_reasons))
    assert np.array_equal(np.asarray(out.dnorms),
                          np.asarray(out2.dnorms))


def test_min_restarts_floor_counts_screened_as_nonsurvivors():
    a = small_matrix()
    # keep=2 survivors < min_restarts=4 -> typed floor error on every
    # harvest path (the same funnel quarantined lanes hit)
    with pytest.raises(InsufficientRestarts, match="SCREENED"):
        nmfconsensus(a, ks=(2,), restarts=6, seed=1,
                     solver_cfg=screened_cfg(screen_keep=2),
                     min_restarts=4, use_mesh=False)
    # at the floor: passes
    res = nmfconsensus(a, ks=(2,), restarts=6, seed=1,
                       solver_cfg=screened_cfg(screen_keep=4),
                       min_restarts=4, use_mesh=False)
    assert res.quality == "exact"  # screening's exact phase IS exact


def test_keep_factors_refused():
    a = small_matrix()
    with pytest.raises(ValueError, match="keep_factors"):
        sweep_one_k(a, jax.random.key(0), 2, 6, screened_cfg(),
                    InitConfig(), keep_factors=True)


def test_screen_keep_equal_restarts_solves_everything():
    """keep == restarts: nothing screened out; every lane's results
    equal the plain vmap-engine sweep bit for bit (the screening layer
    reduces to a no-op reordering)."""
    a = small_matrix()
    key = jax.random.fold_in(jax.random.key(5), 2)
    out_s = sweep_one_k(a, key, 2, 6, screened_cfg(screen_keep=6),
                        InitConfig())
    out_v = sweep_one_k(a, key, 2, 6,
                        SolverConfig(algorithm="mu", max_iter=200,
                                     backend="vmap"), InitConfig())
    assert not np.any(np.asarray(out_s.stop_reasons)
                      == int(StopReason.SCREENED))
    assert np.array_equal(np.asarray(out_s.dnorms),
                          np.asarray(out_v.dnorms))
    assert np.array_equal(np.asarray(out_s.labels),
                          np.asarray(out_v.labels))
    assert np.array_equal(np.asarray(out_s.consensus),
                          np.asarray(out_v.consensus))


def test_restart_factors_reproduces_screened_survivor():
    """restart_factors strips the screening fields (solve() refuses
    them), so a survivor lane recomputes bit-identically from its
    canonical key — the recompute-by-key contract under screening."""
    from nmfx import restart_factors

    a = small_matrix()
    key = jax.random.fold_in(jax.random.key(123), 2)
    out = sweep_one_k(a, key, 2, RESTARTS, screened_cfg(), InitConfig())
    surv = np.nonzero(np.asarray(out.stop_reasons)
                      != int(StopReason.SCREENED))[0]
    i = int(surv[0])
    r = restart_factors(a, 2, i, restarts=RESTARTS, seed=123,
                        solver_cfg=screened_cfg())
    assert np.asarray(r.dnorm).tobytes() == \
        np.asarray(out.dnorms)[i].tobytes()


def test_screened_sweep_through_nmfconsensus_and_grid_exec_guard():
    a = small_matrix()
    res = nmfconsensus(a, ks=(2, 3), restarts=6, seed=2,
                       solver_cfg=screened_cfg(), use_mesh=False)
    assert set(res.per_k) == {2, 3}
    with pytest.raises(ValueError, match="grid_exec='grid'"):
        nmfconsensus(a, ks=(2, 3), restarts=6, seed=2,
                     solver_cfg=screened_cfg(), grid_exec="grid",
                     use_mesh=False)
