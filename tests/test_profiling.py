"""Profiling subsystem tests (SURVEY.md §5: runtime flag replacing the
reference's compile-time PROFILE_* macros, libnmf common.h:27-45)."""

import jax.numpy as jnp
import pytest

from nmfx.api import nmfconsensus
from nmfx.profiling import NullProfiler, Profiler


def test_phase_accumulation():
    prof = Profiler()
    with prof:
        with prof.phase("a") as sync:
            sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        with prof.phase("a"):
            pass
        with prof.phase("b"):
            pass
    assert prof.phases["a"].count == 2
    assert prof.phases["b"].count == 1
    assert prof.total_seconds() > 0
    report = prof.report()
    assert "a" in report and "b" in report and "total" in report


def test_phase_records_on_exception():
    prof = Profiler()
    try:
        with prof.phase("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert prof.phases["boom"].count == 1


def test_pipeline_with_profiler(two_group_data):
    prof = Profiler()
    with prof:
        nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                     use_mesh=False, profiler=prof)
    assert "solve.k=2" in prof.phases
    assert "rank_selection" in prof.phases
    assert prof.phases["solve.k=2"].seconds > 0


def test_null_profiler_is_transparent(two_group_data):
    prof = NullProfiler()
    with prof:
        r = nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                         use_mesh=False, profiler=prof)
    assert r.per_k[2].consensus.shape[0] == two_group_data.shape[1]
    assert prof.report() == "profiling disabled"


@pytest.mark.slow
def test_trace_capture(tmp_path):
    trace_dir = str(tmp_path / "trace")
    prof = Profiler(trace_dir=trace_dir)
    with prof:
        with prof.phase("mm") as sync:
            sync(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    import os

    # jax writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    found = [f for _, _, fs in os.walk(trace_dir) for f in fs]
    assert any(f.endswith(".pb") or f.endswith(".json.gz") for f in found)
    assert "device trace" in prof.report()
