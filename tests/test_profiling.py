"""Profiling subsystem tests (SURVEY.md §5: runtime flag replacing the
reference's compile-time PROFILE_* macros, libnmf common.h:27-45)."""

import jax.numpy as jnp
import pytest

from nmfx.api import nmfconsensus
from nmfx.profiling import NullProfiler, Profiler


def test_phase_accumulation():
    prof = Profiler()
    with prof:
        with prof.phase("a") as sync:
            sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        with prof.phase("a"):
            pass
        with prof.phase("b"):
            pass
    assert prof.phases["a"].count == 2
    assert prof.phases["b"].count == 1
    assert prof.total_seconds() > 0
    report = prof.report()
    assert "a" in report and "b" in report and "total" in report


def test_phase_records_on_exception():
    prof = Profiler()
    try:
        with prof.phase("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert prof.phases["boom"].count == 1


def test_pipeline_with_profiler(two_group_data):
    prof = Profiler()
    with prof:
        nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                     use_mesh=False, profiler=prof)
    assert "solve.k=2" in prof.phases
    # default harvest is streamed: rank selection runs in harvest
    # workers under the overlap-classed phase name
    assert "post.rank_selection" in prof.phases
    assert prof.phases["solve.k=2"].seconds > 0


def test_sequential_harvest_keeps_legacy_phases(two_group_data):
    prof = Profiler()
    with prof:
        nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                     use_mesh=False, harvest="sequential", profiler=prof)
    assert "rank_selection" in prof.phases
    assert "device_to_host" in prof.phases


def test_null_profiler_is_transparent(two_group_data):
    prof = NullProfiler()
    with prof:
        r = nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                         use_mesh=False, profiler=prof)
    assert r.per_k[2].consensus.shape[0] == two_group_data.shape[1]
    assert prof.report() == "profiling disabled"


def test_add_seconds_concurrent_exact():
    """ISSUE 5 satellite: harvest workers record phases from their own
    threads. N threads x M additions to the same phase must neither
    drop nor double-count — the totals are EXACT (integer-representable
    increments, so float addition is associative here)."""
    import threading

    prof = Profiler()
    threads_n, m = 8, 250

    def work():
        for _ in range(m):
            prof.add_seconds("post.rank_selection", 0.5)
            prof.mark("xfer.h2d_cache_hit")

    threads = [threading.Thread(target=work) for _ in range(threads_n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rec = prof.phases["post.rank_selection"]
    assert rec.count == threads_n * m
    assert rec.seconds == 0.5 * threads_n * m
    assert prof.phases["xfer.h2d_cache_hit"].count == threads_n * m


def test_phase_context_concurrent_counts():
    """The phase() context manager funnels through the same locked
    accumulation: concurrent regions across threads keep exact counts."""
    import threading

    prof = Profiler()
    m = 100

    def work(name):
        for _ in range(m):
            with prof.phase(name):
                pass

    threads = [threading.Thread(target=work, args=(f"t{i % 2}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert prof.phases["t0"].count == 2 * m
    assert prof.phases["t1"].count == 2 * m


def test_audit_overlap_split():
    """Overlap-classed phases (xfer.*, post.*) stay OUT of the
    phase-sum-vs-wall book and are reported as an overlap ratio."""
    prof = Profiler()
    prof.add_seconds("solve.grid", 1.0)
    prof.add_seconds("device_to_host", 0.25)
    prof.add_seconds("xfer.d2h_overlap", 0.4)
    prof.add_seconds("post.rank_selection", 0.3)
    assert prof.phases["xfer.d2h_overlap"].overlapped
    assert not prof.phases["solve.grid"].overlapped
    a = prof.audit(2.0)
    assert a["phase_sum_s"] == 1.25
    assert a["overlap_s"] == pytest.approx(0.7)
    assert a["unattributed_s"] == pytest.approx(0.75)
    assert a["coverage"] == pytest.approx(0.625)
    assert a["overlap_ratio"] == pytest.approx(0.35)
    # total_seconds (the report's denominator) is the sequential sum
    assert prof.total_seconds() == 1.25
    report = prof.report()
    assert "~xfer.d2h_overlap" in report
    assert "overlapped" in report


def test_audit_zero_wall_division_guards():
    """ISSUE 10 satellite: the zero-wall edge — an empty (or
    instantaneous) region must audit to clean zeros, never a
    ZeroDivisionError. Pins the explicit wall=0.0 path and the
    no-phases default path (total_seconds() == 0)."""
    prof = Profiler()
    a = prof.audit(0.0)
    assert a == {"wall_s": 0.0, "phase_sum_s": 0.0,
                 "unattributed_s": 0.0, "coverage": 0.0,
                 "overlap_s": 0.0, "overlap_ratio": 0.0}
    # marks only: zero seconds everywhere, default wall is the (zero)
    # sequential sum
    prof.mark("compile.cache_hit")
    a = prof.audit()
    assert a["wall_s"] == 0.0
    assert a["coverage"] == 0.0 and a["overlap_ratio"] == 0.0
    # report renders without dividing by the zero total
    report = prof.report()
    assert "compile.cache_hit" in report


def test_audit_all_overlap_edge():
    """ISSUE 10 satellite: EVERY phase overlap-classed (a pure
    worker-thread region — the streamed-harvest books when the main
    thread recorded nothing). The sequential sum is zero, so the
    default-wall audit divides by zero wall; both ratios must guard,
    and the report must ``~``-tag every row with the share column
    dashed."""
    prof = Profiler()
    prof.add_seconds("xfer.d2h_overlap", 0.4)
    prof.add_seconds("post.rank_selection", 0.6)
    assert prof.total_seconds() == 0.0
    a = prof.audit()  # wall falls back to the zero sequential sum
    assert a["wall_s"] == 0.0
    assert a["phase_sum_s"] == 0.0
    assert a["coverage"] == 0.0
    assert a["overlap_s"] == pytest.approx(1.0)
    assert a["overlap_ratio"] == 0.0  # guarded, not inf
    # against a real wall the overlap ratio books normally
    assert prof.audit(2.0)["overlap_ratio"] == pytest.approx(0.5)
    report = prof.report()
    for line in report.splitlines():
        if "d2h_overlap" in line or "rank_selection" in line:
            assert line.startswith("~")
            assert line.rstrip().endswith("-")


def test_phase_sum_audit_on_profiled_run(two_group_data):
    """The audit on a REAL profiled run: the sequential phases must
    explain the wall (no hidden async time migrating between phases —
    the r05 failure mode), and never exceed it."""
    prof = Profiler()
    with prof:
        nmfconsensus(two_group_data, ks=(2,), restarts=2, max_iter=40,
                     use_mesh=False, harvest="sequential", profiler=prof)
    a = prof.audit()
    assert a["wall_s"] > 0
    # flat sequential phases: their sum cannot exceed the enclosing wall
    assert a["phase_sum_s"] <= a["wall_s"] * 1.02 + 0.02
    # and they must explain most of it (compile+solve+transfer+selection
    # all run under named phases; only loop glue is unattributed)
    assert a["coverage"] > 0.5


@pytest.mark.slow
def test_trace_capture(tmp_path):
    trace_dir = str(tmp_path / "trace")
    prof = Profiler(trace_dir=trace_dir)
    with prof:
        with prof.phase("mm") as sync:
            sync(jnp.ones((16, 16)) @ jnp.ones((16, 16)))
    import os

    # jax writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    found = [f for _, _, fs in os.walk(trace_dir) for f in fs]
    assert any(f.endswith(".pb") or f.endswith(".json.gz") for f in found)
    assert "device trace" in prof.report()
