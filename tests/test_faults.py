"""Chaos suite (ISSUE 7 tentpole): every registered fault site armed
against the e2e consensus path, asserting the recovery contract —
**bit-identical results where recovery is exact** (h2d fallback, harvest
re-run, deserialize-recompile, solo retry after a failed packed/compile
attempt), **typed errors otherwise** (``FaultInjected``,
``InsufficientRestarts``, ``RequestFailed``, ``ServerCrashed``), and
**bounded wall time always** — zero hangs (every ``Future.result`` here
carries a timeout, and ``tests/conftest.py``'s per-test hang guard
dumps all thread stacks and kills the run if a regression wedges one of
these threaded paths).

The quarantine-exactness block is the acceptance criterion's core: a
sweep with an injected non-finite lane must produce consensus /
rho / membership identical to the same sweep without that restart,
pinned across the grid (slot-scheduled), vmapped-dense, and packed
engines. The reference side is computed from the CLEAN run's
per-restart outputs (surviving lanes are bit-identical by lane
independence), never from a re-keyed smaller sweep.
"""

import time

import numpy as np
import pytest

from nmfx import faults
from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.faults import FaultInjected, InsufficientRestarts
from nmfx.solvers.base import StopReason

KS = (2, 3)
RESTARTS = 3
MAX_ITER = 20
SEED = 11


@pytest.fixture(autouse=True)
def _pristine_registry():
    """Every test starts and ends with nothing armed and the warn-once
    ledger clear (warn_once fires once per category per PROCESS — the
    ledger reset is what lets each test assert its own warning)."""
    faults.disarm()
    faults._reset_warned()
    yield
    faults.disarm()
    faults._reset_warned()


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=48, n_per_group=8, seed=5)


def _consensus(data, *, algorithm="mu", backend="auto", grid_exec="auto",
               ks=KS, restarts=RESTARTS, **kw):
    from nmfx.api import nmfconsensus

    scfg = SolverConfig(algorithm=algorithm, backend=backend,
                        max_iter=MAX_ITER)
    return nmfconsensus(data, ks=ks, restarts=restarts, seed=SEED,
                        solver_cfg=scfg, use_mesh=False, **kw)


def _sweep(data, *, algorithm="mu", backend="auto", grid_exec="auto",
           ks=KS, restarts=RESTARTS):
    import jax

    from nmfx.sweep import sweep

    ccfg = ConsensusConfig(ks=ks, restarts=restarts, seed=SEED,
                           grid_exec=grid_exec)
    scfg = SolverConfig(algorithm=algorithm, backend=backend,
                        max_iter=MAX_ITER)
    out = sweep(np.asarray(data), ccfg, scfg, InitConfig(), None)
    return {k: jax.device_get(v) for k, v in out.items()}


def assert_result_bit_equal(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        s, q = got.per_k[k], ref.per_k[k]
        for field in ("consensus", "rho", "membership", "order",
                      "iterations", "dnorms", "stop_reasons", "best_w",
                      "best_h"):
            assert np.array_equal(np.asarray(getattr(s, field)),
                                  np.asarray(getattr(q, field))), \
                f"{field} k={k}"


# ---------------------------------------------------------------------
# registry semantics (no device work)
# ---------------------------------------------------------------------

def test_unknown_site_rejected():
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.arm("no.such.site", every=1)
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.FaultConfig(site="typo.site")


def test_fault_config_validation():
    with pytest.raises(ValueError, match="every"):
        faults.arm("h2d.transfer", every=0)
    with pytest.raises(ValueError, match="max_fires"):
        faults.arm("h2d.transfer", max_fires=0)
    with pytest.raises(ValueError, match="rate"):
        faults.arm("solve.nonfinite", rate=1.5)
    # lane-rate sites demand an explicit rate or lane set
    with pytest.raises(ValueError, match="rate"):
        faults.arm("solve.nonfinite")


def test_every_and_max_fires_schedule():
    faults.arm("compile.build", every=2, max_fires=2)
    fired = [faults.fire("compile.build") for _ in range(8)]
    # hits 2 and 4 fire; max_fires=2 then keeps the site inert
    assert fired == [False, True, False, True, False, False, False,
                     False]
    assert faults.hits("compile.build") == 8
    assert faults.fires("compile.build") == 2


def test_inject_raises_typed():
    faults.arm("persist.deserialize", every=1)
    with pytest.raises(FaultInjected) as exc:
        faults.inject("persist.deserialize")
    assert exc.value.site == "persist.deserialize"
    assert exc.value.hit == 1


def test_scoped_restores_previous_policy():
    assert faults.armed("h2d.transfer") is None
    faults.arm("h2d.transfer", every=3)
    with faults.scoped("h2d.transfer", every=1):
        assert faults.armed("h2d.transfer").every == 1
    assert faults.armed("h2d.transfer").every == 3
    faults.disarm("h2d.transfer")
    with faults.scoped("h2d.transfer", every=5):
        assert faults.armed("h2d.transfer").every == 5
    assert faults.armed("h2d.transfer") is None


def test_poison_restarts_deterministic():
    # explicit lanes: exact selection, restart bounds respected
    faults.arm("solve.nonfinite", lanes=((2, 1), (3, 7)))
    assert faults.poison_restarts(2, 3) == (1,)
    assert faults.poison_restarts(3, 3) == ()  # lane 7 out of range
    assert faults.poison_restarts(4, 3) == ()
    # rate arming: seeded, process-stable, k-dependent
    faults.arm("solve.nonfinite", rate=0.5, seed=7)
    first = faults.poison_restarts(2, 64)
    assert faults.poison_restarts(2, 64) == first
    assert 8 < len(first) < 56  # a real subset, not all-or-nothing
    faults.arm("solve.nonfinite", rate=0.5, seed=8)
    assert faults.poison_restarts(2, 64) != first
    faults.arm("solve.nonfinite", rate=0.0, seed=7)
    assert faults.poison_restarts(2, 64) == ()
    assert faults.poison_restarts(2, 0) == ()


def test_trace_token_fences_trace_affecting_sites():
    assert faults.trace_token() is None
    faults.arm("h2d.transfer", every=1)  # host-side: no token change
    assert faults.trace_token() is None
    faults.arm("solve.nonfinite", lanes=((2, 0),))
    tok1 = faults.trace_token()
    assert tok1 is not None
    faults.arm("solve.nonfinite", lanes=((2, 1),))
    tok2 = faults.trace_token()
    assert tok2 is not None and tok2 != tok1  # re-arm bumps generation
    faults.disarm("solve.nonfinite")
    assert faults.trace_token() is None


def test_checkpoint_sites_registered_and_trace_inert():
    """ISSUE 9: the durability site family exists, and arming any of it
    never perturbs the trace token — the checkpoint sites are host-side
    only, so an armed process keeps byte-identical builder/executable
    cache keys and can never be served (or produce) a stale executable
    through them. A checkpoint site that DID alter traced code would
    have to join faults._TRACE_SITES and this test."""
    for site in ("ckpt.write", "ckpt.load", "proc.preempt"):
        assert site in faults.SITES
        assert site not in faults._TRACE_SITES
        with faults.scoped(site, every=1):
            assert faults.trace_token() is None
            # hit-counted like every host-side site
            assert faults.fire(site)
        assert faults.armed(site) is None


def test_proc_preempt_raises_preempted_from_chunk_executor(small_data):
    """An armed proc.preempt fires between a chunk's solve and its
    commit and surfaces as the typed checkpoint.Preempted — a
    BaseException, so no broad except-Exception recovery layer can
    swallow a preemption and keep computing."""
    import jax

    from nmfx import checkpoint as ckpt
    from nmfx.config import ConsensusConfig, InitConfig, SolverConfig

    assert not issubclass(ckpt.Preempted, Exception)
    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=0)
    scfg = SolverConfig(algorithm="mu", max_iter=10)
    a_dev = jax.numpy.asarray(small_data, jax.numpy.float32)
    with faults.scoped("proc.preempt", every=1):
        with pytest.raises(ckpt.Preempted):
            ckpt.solve_chunk_host(a_dev, 2, 0, 2, ccfg, scfg,
                                  InitConfig())
    # unarmed: the same call commits normally
    rec = ckpt.solve_chunk_host(a_dev, 2, 0, 2, ccfg, scfg, InitConfig())
    assert rec.labels.shape == (2, small_data.shape[1])


def test_warn_once_per_category():
    with pytest.warns(RuntimeWarning, match="first"):
        faults.warn_once("chaos-test-cat", "first")
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # a second warning would raise
        faults.warn_once("chaos-test-cat", "second (suppressed)")
    with pytest.warns(RuntimeWarning, match="other"):
        faults.warn_once("chaos-test-cat-2", "other")


# ---------------------------------------------------------------------
# exact-recovery sites: bit-identical results through the fallback
# ---------------------------------------------------------------------

def test_h2d_transfer_fault_falls_back_direct_exact(small_data):
    """An injected input-transfer failure degrades to a direct uncached
    h2d (warn-once); the device values — and every downstream result —
    are bit-identical to the cached-placement run."""
    from nmfx.datasets import two_group_matrix

    fresh = two_group_matrix(n_genes=48, n_per_group=8, seed=9)
    faults.arm("h2d.transfer", every=1)
    with pytest.warns(RuntimeWarning, match="h2d-direct-fallback"):
        faulted = _consensus(fresh)
    assert faults.fires("h2d.transfer") >= 1
    faults.disarm("h2d.transfer")
    clean = _consensus(fresh)  # cache path, same content
    assert_result_bit_equal(faulted, clean)


def test_harvest_worker_death_sequential_fallback_exact(small_data):
    """Every streamed-harvest worker dying falls back to sequential
    re-harvest of the same device outputs — exact recovery."""
    clean = _consensus(small_data)
    faults.arm("harvest.worker", every=1)
    with pytest.warns(RuntimeWarning, match="harvest-worker-fallback"):
        faulted = _consensus(small_data, harvest="streamed")
    assert faults.fires("harvest.worker") == len(KS)
    assert_result_bit_equal(faulted, clean)


def test_persist_deserialize_fault_recompiles_exact(small_data,
                                                    tmp_path):
    """A corrupt/injected persisted-executable read drops the entry,
    warns once, and recompiles — the recompiled executable is
    bit-identical (the PR 4 fallback, now rehearsable on demand)."""
    from nmfx.config import ExecCacheConfig
    from nmfx.exec_cache import ExecCache, compile_count

    cfg = ExecCacheConfig(cache_dir=str(tmp_path / "exec"))
    warm = ExecCache(cfg)
    ref = _consensus(small_data, ks=(2,), restarts=2, exec_cache=warm)
    fresh = ExecCache(cfg)  # same disk cache, empty memory LRU
    faults.arm("persist.deserialize", every=1, max_fires=1)
    before = compile_count()
    with pytest.warns(RuntimeWarning, match="recompiling"):
        got = _consensus(small_data, ks=(2,), restarts=2,
                         exec_cache=fresh)
    assert faults.fires("persist.deserialize") == 1
    assert compile_count() == before + 1  # fallback really recompiled
    assert_result_bit_equal(got, ref)


def test_compile_build_fault_direct_is_typed(small_data):
    """Without a retrying layer above it, an injected compile failure
    surfaces as the typed FaultInjected — loud, attributed, bounded."""
    from nmfx.exec_cache import ExecCache

    faults.arm("compile.build", every=1)
    with pytest.raises(FaultInjected) as exc:
        _consensus(small_data, ks=(2,), restarts=2,
                   exec_cache=ExecCache())
    assert exc.value.site == "compile.build"


def test_compile_build_fault_serve_retries_exact(small_data):
    """Through the serving layer the same compile fault is survived:
    the solo dispatch retries (exponential backoff), the second attempt
    compiles, and the served result is bit-identical to the solo run
    through the same layer."""
    from nmfx.exec_cache import ExecCache
    from nmfx.serve import NMFXServer, ServeConfig

    cache = ExecCache()
    scfg = SolverConfig(max_iter=MAX_ITER)
    faults.arm("compile.build", every=1, max_fires=1)
    cfg = ServeConfig(dispatch_retries=1, retry_backoff_s=0.01)
    with pytest.warns(RuntimeWarning, match="solo-dispatch-retry"):
        with NMFXServer(cfg, exec_cache=cache) as srv:
            fut = srv.submit(small_data, ks=(2,), restarts=2, seed=SEED,
                             solver_cfg=scfg)
            got = fut.result(timeout=600)
    assert faults.fires("compile.build") == 1
    from nmfx.api import nmfconsensus

    ref = nmfconsensus(small_data, ks=(2,), restarts=2, seed=SEED,
                       solver_cfg=scfg, use_mesh=False,
                       exec_cache=cache)
    assert_result_bit_equal(got, ref)


# ---------------------------------------------------------------------
# numeric quarantine: the exactness acceptance criterion
# ---------------------------------------------------------------------

def _expected_masked_kresult(out, r_bad: int, k: int):
    """The reference KResult for a rank whose lane ``r_bad`` was
    quarantined, built from the CLEAN sweep output: surviving lanes are
    bit-identical by lane independence, so the survivor-mean consensus
    and the masked fields below ARE "the same sweep without that
    restart"."""
    from nmfx.api import _build_k_result

    labels = np.asarray(out.labels).copy()
    n = labels.shape[1]
    survivors = [r for r in range(labels.shape[0]) if r != r_bad]
    conn = np.zeros((n, n), np.float32)
    for r in survivors:
        lab = labels[r]
        conn += (lab[:, None] == lab[None, :]).astype(np.float32)
    cons = conn / np.float32(len(survivors))
    labels[r_bad] = -1
    stops = np.asarray(out.stop_reasons).copy()
    stops[r_bad] = int(StopReason.NUMERIC_FAULT)
    masked = out._replace(consensus=cons, labels=labels,
                          stop_reasons=stops)
    return _build_k_result(k, masked, "average")


@pytest.mark.parametrize("algorithm,backend,grid_exec", [
    ("mu", "auto", "auto"),      # whole-grid slot-scheduled engine
    ("mu", "vmap", "per_k"),     # vmapped dense engine
    ("hals", "packed", "auto"),  # packed-column engine (shared Grams)
])
def test_quarantine_exactness(small_data, algorithm, backend,
                              grid_exec):
    """The acceptance criterion: one injected non-finite lane in rank 2
    stops with NUMERIC_FAULT and the rank's consensus/rho/membership
    equal the same sweep without that restart; rank 3 (untouched) is
    bit-identical to the clean run end to end."""
    kw = dict(algorithm=algorithm, backend=backend, grid_exec=grid_exec)
    clean_out = _sweep(small_data, **kw)
    clean_res = _consensus(small_data, **kw)
    # poison the WORST clean lane of rank 2 (never the best-restart
    # winner), so best_w/best_h must survive quarantine unchanged
    r_bad = int(np.argmax(np.asarray(clean_out[2].dnorms)))
    assert r_bad != int(np.argmin(np.asarray(clean_out[2].dnorms)))
    faults.arm("solve.nonfinite", lanes=((2, r_bad),))
    faulted = _consensus(small_data, **kw)

    # rank 3 carried no fault: bit-identical end to end
    f3, c3 = faulted.per_k[3], clean_res.per_k[3]
    for field in ("consensus", "rho", "membership", "order",
                  "iterations", "dnorms", "stop_reasons", "best_w",
                  "best_h"):
        assert np.array_equal(np.asarray(getattr(f3, field)),
                              np.asarray(getattr(c3, field))), field

    # rank 2: the poisoned lane stopped with NUMERIC_FAULT...
    f2 = faulted.per_k[2]
    stops = np.asarray(f2.stop_reasons)
    assert stops[r_bad] == int(StopReason.NUMERIC_FAULT)
    survivors = [r for r in range(RESTARTS) if r != r_bad]
    # ...surviving lanes are bit-identical to the clean run...
    clean2 = clean_out[2]
    assert np.array_equal(stops[survivors],
                          np.asarray(clean2.stop_reasons)[survivors])
    assert np.array_equal(np.asarray(f2.iterations)[survivors],
                          np.asarray(clean2.iterations)[survivors])
    assert np.array_equal(np.asarray(f2.dnorms)[survivors],
                          np.asarray(clean2.dnorms)[survivors])
    # ...and consensus/rho/membership/order/best equal the same sweep
    # without that restart (reference from the clean lanes)
    ref2 = _expected_masked_kresult(clean2, r_bad, 2)
    for field in ("consensus", "rho", "membership", "order", "best_w",
                  "best_h"):
        assert np.array_equal(np.asarray(getattr(f2, field)),
                              np.asarray(getattr(ref2, field))), field


def test_quarantine_insufficient_restarts_floor(small_data):
    """The loud floor: survivors below min_restarts raise the typed
    InsufficientRestarts instead of serving a thin consensus; at the
    default floor (1) a single survivor still serves."""
    faults.arm("solve.nonfinite", lanes=((2, 0),))
    with pytest.raises(InsufficientRestarts, match="min_restarts=2"):
        _consensus(small_data, backend="vmap", grid_exec="per_k",
                   ks=(2,), restarts=2, min_restarts=2)
    # same armed generation (no re-arm): the builder is reused and the
    # default floor accepts the single survivor
    res = _consensus(small_data, backend="vmap", grid_exec="per_k",
                     ks=(2,), restarts=2)
    stops = np.asarray(res.per_k[2].stop_reasons)
    assert stops[0] == int(StopReason.NUMERIC_FAULT)
    assert stops[1] != int(StopReason.NUMERIC_FAULT)


def test_quarantine_all_lanes_faulted_raises(small_data):
    faults.arm("solve.nonfinite", lanes=((2, 0), (2, 1)))
    with pytest.raises(InsufficientRestarts, match="0 of 2"):
        _consensus(small_data, backend="vmap", grid_exec="per_k",
                   ks=(2,), restarts=2)


# ---------------------------------------------------------------------
# scheduler watchdog: no Future is ever left pending
# ---------------------------------------------------------------------

def _fake_raw(req):
    from nmfx.sweep import KSweepOutput

    m, n = req.a.shape
    out = {}
    for k in req.ks:
        labels = np.arange(n) * k // n
        cons = (labels[:, None] == labels[None, :]).astype(np.float32)
        out[k] = KSweepOutput(
            consensus=cons,
            iterations=np.full(req.restarts, 7, np.int32),
            dnorms=np.linspace(0.5, 0.6, req.restarts).astype(
                np.float32),
            stop_reasons=np.zeros(req.restarts, np.int32),
            labels=np.tile(labels, (req.restarts, 1)).astype(np.int32),
            best_w=np.ones((m, k), np.float32),
            best_h=np.ones((k, n), np.float32))
    return out


class _FakeEngine:
    """Minimal scriptable Engine for thread-level chaos (no device)."""

    def __init__(self, compat="shared", solo_failures=0,
                 packed_fails=False):
        self.compat = compat
        self.solo_failures = solo_failures
        self.packed_fails = packed_fails
        self.solo_calls = 0
        self.packed_calls = 0

    def compatibility_key(self, req):
        return self.compat

    def place(self, req):
        return None

    def dispatch_solo(self, req, placed, scfg):
        self.solo_calls += 1
        if self.solo_failures > 0:
            self.solo_failures -= 1
            raise RuntimeError("transient dispatch failure")
        return _fake_raw(req)

    def dispatch_packed(self, reqs, placed):
        self.packed_calls += 1
        if self.packed_fails:
            raise RuntimeError("packed lane composition failed")
        return [_fake_raw(r) for r in reqs]


def _mat(m=8, n=6, seed=0):
    rng = np.random.default_rng(seed)
    return rng.random((m, n)).astype(np.float32)


def test_scheduler_death_no_future_left_pending():
    """The acceptance property: the scheduler dies with one request
    IN FLIGHT (popped, undispatched) and more queued — the watchdog
    resolves every one with a typed ServerCrashed chaining the injected
    fault; nothing hangs, and with restart_scheduler=False subsequent
    submits are refused typed."""
    from nmfx.serve import NMFXServer, ServeConfig, ServerCrashed

    faults.arm("serve.scheduler", every=1)
    cfg = ServeConfig(restart_scheduler=False, watchdog_interval_s=0.05,
                      pack=False)
    srv = NMFXServer(cfg, engine=_FakeEngine(compat=None), start=False)
    with pytest.warns(RuntimeWarning, match="scheduler-crash"):
        futs = [srv.submit(_mat(), ks=(2,), restarts=2)
                for _ in range(3)]
        srv.resume()
        for f in futs:
            with pytest.raises(ServerCrashed) as exc:
                f.result(timeout=30)
            assert isinstance(exc.value.__cause__, FaultInjected)
            assert exc.value.__cause__.site == "serve.scheduler"
    assert all(f.done() for f in futs)  # zero pending futures
    assert srv.stats()["failed"] == 3
    with pytest.raises(ServerCrashed):
        srv.submit(_mat(), ks=(2,), restarts=2)
    srv.close()  # bounded: close after crash must not hang either


def test_scheduler_crash_restarts_and_serves_again():
    """restart_scheduler=True: pending work at crash time fails loudly
    (never silently replayed), then a fresh scheduler serves new
    submissions on the same server."""
    from nmfx.serve import NMFXServer, ServeConfig, ServerCrashed

    faults.arm("serve.scheduler", every=1, max_fires=1)
    cfg = ServeConfig(restart_scheduler=True, watchdog_interval_s=0.05,
                      pack=False)
    with NMFXServer(cfg, engine=_FakeEngine(compat=None)) as srv:
        with pytest.warns(RuntimeWarning, match="scheduler restarted"):
            f1 = srv.submit(_mat(), ks=(2,), restarts=2)
            with pytest.raises(ServerCrashed):
                f1.result(timeout=30)
        f2 = srv.submit(_mat(), ks=(2,), restarts=2)
        res = f2.result(timeout=30)  # the restarted scheduler serves
    assert res.per_k[2] is not None
    assert srv.stats()["failed"] == 1
    assert srv.stats()["completed"] == 1


def test_packed_dispatch_failure_degrades_to_solo():
    """A failed packed dispatch retries each mate solo: failure
    isolation becomes per-request and every future resolves with a
    RESULT (warn-once on the degradation)."""
    from nmfx.serve import NMFXServer, ServeConfig

    eng = _FakeEngine(compat="shared", packed_fails=True)
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        with pytest.warns(RuntimeWarning,
                          match="packed-dispatch-fallback"):
            f1 = srv.submit(_mat(), ks=(2,), restarts=2)
            f2 = srv.submit(_mat(), ks=(2,), restarts=2)
            srv.resume()
            r1 = f1.result(timeout=30)
            r2 = f2.result(timeout=30)
    assert eng.packed_calls == 1 and eng.solo_calls == 2
    assert r1.per_k[2] is not None and r2.per_k[2] is not None
    assert srv.stats()["completed"] == 2


def test_solo_retry_with_backoff_recovers():
    """A transient solo failure is retried with exponential backoff and
    the request completes — no typed error reaches the caller."""
    from nmfx.serve import NMFXServer, ServeConfig

    eng = _FakeEngine(compat=None, solo_failures=2)
    cfg = ServeConfig(dispatch_retries=2, retry_backoff_s=0.01)
    t0 = time.monotonic()
    with NMFXServer(cfg, engine=eng) as srv:
        with pytest.warns(RuntimeWarning, match="solo-dispatch-retry"):
            f = srv.submit(_mat(), ks=(2,), restarts=2)
            res = f.result(timeout=30)
    assert res.per_k[2] is not None
    assert eng.solo_calls == 3  # 2 failures + the succeeding attempt
    assert time.monotonic() - t0 >= 0.01 + 0.02  # backoff really slept
    assert srv.stats()["completed"] == 1 and srv.stats()["failed"] == 0


def test_serve_harvest_worker_fault_recovers_inline():
    """The serve completion worker passes the harvest.worker site too:
    an injected worker death re-runs that rank's harvest inline and the
    request still completes."""
    from nmfx.serve import NMFXServer, ServeConfig

    faults.arm("harvest.worker", every=1, max_fires=1)
    with NMFXServer(ServeConfig(), engine=_FakeEngine(compat=None)) \
            as srv:
        with pytest.warns(RuntimeWarning,
                          match="harvest-worker-fallback"):
            f = srv.submit(_mat(), ks=(2,), restarts=2)
            res = f.result(timeout=30)
    assert res.per_k[2] is not None
    assert faults.fires("harvest.worker") == 1
    assert srv.stats()["completed"] == 1


def test_scheduler_crash_emits_flight_recorder_dump(tmp_path):
    """ISSUE 10 acceptance: a forced scheduler crash dumps the flight
    recorder — the postmortem artifact names the armed fault site (the
    injected serve.scheduler fire) and the watchdog's resolution
    events, turning the warn-once line into inspectable JSON."""
    import json
    import os

    from nmfx.obs import flight
    from nmfx.serve import NMFXServer, ServeConfig, ServerCrashed

    import numpy as np

    from nmfx.config import SolverConfig
    from nmfx.obs import costmodel, slo

    flight.configure(str(tmp_path))
    # fresh event ring: the recorder is process-global and the earlier
    # watchdog tests in this module left their own crash events on it
    flight.default_recorder().clear()
    # seed the perf drill-down ring and the SLO status the postmortem
    # must now embed (ISSUE 14: a crash artifact carries perf/SLO
    # context, not just fault events)
    perf_rec = costmodel.attribute_dispatch(
        "crash-context", SolverConfig(), 32, 16,
        {2: np.array([10, 10])}, 0.05)
    assert perf_rec is not None
    slo.SLOEngine().evaluate()
    try:
        faults.arm("serve.scheduler", every=1)
        cfg = ServeConfig(restart_scheduler=False,
                          watchdog_interval_s=0.05, pack=False)
        srv = NMFXServer(cfg, engine=_FakeEngine(compat=None),
                         start=False)
        with pytest.warns(RuntimeWarning, match="scheduler-crash"):
            futs = [srv.submit(_mat(), ks=(2,), restarts=2)
                    for _ in range(2)]
            srv.resume()
            for f in futs:
                with pytest.raises(ServerCrashed):
                    f.result(timeout=30)
        # the dump is written by the watchdog thread right after it
        # resolves the strays; bounded wait for the artifact
        deadline = time.monotonic() + 10
        dump_path = None
        while time.monotonic() < deadline and dump_path is None:
            hits = [f for f in os.listdir(tmp_path)
                    if f.startswith("flight_")
                    and "serve-scheduler-crash" in f]
            if hits:
                dump_path = os.path.join(tmp_path, hits[0])
            else:
                time.sleep(0.05)
        srv.close()
        assert dump_path is not None, "no flight dump written"
        art = json.loads(open(dump_path).read())
        assert art["reason"] == "serve-scheduler-crash"
        # the armed fault site is in the postmortem twice over: still
        # armed at dump time, and its FIRE is on the event ring
        assert "serve.scheduler" in art["armed_fault_sites"]
        fires = [e for e in art["events"]
                 if e["category"] == "fault.serve.scheduler"]
        assert fires and fires[0]["site"] == "serve.scheduler"
        # ... as are the watchdog's resolution actions, one per
        # stranded future plus the crash summary
        wd = [e for e in art["events"]
              if e["category"] == "serve.watchdog"]
        assert sum(1 for e in wd
                   if e["action"] == "resolve_stranded") == 2
        crash = next(e for e in wd
                     if e["action"] == "scheduler_crash")
        assert crash["resolved"] == 2
        assert "FaultInjected" in crash["error"] \
            or "injected fault" in crash["error"]
        # ... and the perf/SLO context rides in the payload (ISSUE 14):
        # the recent_attributions drill-down ring tail and the latest
        # SLO engine status — a postmortem answers "was the process
        # healthy and within budget", not just "what faults fired"
        assert any(rec["kind"] == "crash-context"
                   for rec in art["perf_recent"])
        assert art["slo"] is not None
        assert "availability" in art["slo"]["objectives"]
        # in-process artifact mirrors the file
        assert flight.last_dump()["reason"] == "serve-scheduler-crash"
    finally:
        flight.configure(None)
