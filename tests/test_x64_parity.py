"""f64 parity for the FULL solver matrix + NNDSVD, in subprocesses.

``jax_enable_x64`` is global and must be set before any JAX use, so each
case runs in a dedicated subprocess (the in-process suite pins the f32
8-device CPU platform). ``SolverConfig.dtype="float64"`` is the documented
parity path vs the reference's f64 BLAS (``libnmf/*.c`` runs entirely in
doubles): every solver is driven lockstep against the f64 NumPy
transliterations of the reference math from tests/test_golden.py and must
agree at rtol 1e-10 — far beyond anything an f32 run could produce, so this
also guards the dtype plumbing end to end.
"""

import os
import subprocess
import sys
import textwrap

import pytest

#: f64 lockstep-vs-reference-math comparisons are the heaviest per-test
#: tier of the pyramid; tier-1 keeps the f32 equivalents
pytestmark = pytest.mark.slow

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

#: (algorithm, transliteration, iterations). Iteration counts are kept small
#: enough that the pg family's discrete line-search decisions cannot drift
#: across the two implementations' reduction orders, but large enough that
#: f32 execution would visibly diverge from the f64 oracle.
_CASES = [
    ("mu", "_mu_numpy", 25),
    ("als", "_als_numpy", 8),
    ("neals", "_neals_numpy", 8),
    ("pg", "_pg_numpy", 6),
    ("alspg", "_alspg_numpy", 5),
    ("kl", "_kl_numpy", 25),
    ("snmf", "_snmf_numpy", 10),
    ("hals", "_hals_numpy", 12),
]

_PRELUDE = f"""
    import jax
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_platforms", "cpu")
    import sys
    sys.path.insert(0, {_TESTS_DIR!r})
    import numpy as np
    import jax.numpy as jnp
"""


def _run_case(code: str) -> None:
    proc = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


@pytest.mark.parametrize("algo,oracle,iters", _CASES,
                         ids=[c[0] for c in _CASES])
def test_f64_solver_lockstep_vs_reference_math(algo, oracle, iters):
    extra = ""
    call = f"{oracle}(a, w0, h0, iters={iters})"
    if algo == "snmf":
        # snmf's transliteration takes its regularizers explicitly; mirror
        # the solver defaults (beta, eta=max(A)^2)
        call = (f"{oracle}(a, w0, h0, iters={iters}, beta=0.01, "
                "eta=float(np.max(a)) ** 2)")
    if algo in ("pg", "alspg"):
        extra = ", tol_pg=0.0"
    _run_case(f"""
{_PRELUDE}
    from test_golden import {oracle}, _problem
    from nmfx.config import SolverConfig
    from nmfx.solvers import solve

    a, w0, h0 = _problem(seed=12)
    w_ref, h_ref = {call}
    cfg = SolverConfig(algorithm={algo!r}, max_iter={iters},
                       dtype="float64", use_class_stop=False,
                       use_tol_checks=False{extra})
    res = solve(a, w0, h0, cfg)
    assert res.w.dtype == jnp.float64, res.w.dtype
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=1e-10)
    print("OK")
    """)


def test_f64_nndsvd_lockstep_vs_reference_math():
    _run_case(f"""
{_PRELUDE}
    from test_golden import _nndsvd_numpy, _problem
    from nmfx.init import nndsvd_init

    a, _, _ = _problem(seed=12)
    w_ref, h_ref = _nndsvd_numpy(a, 3)
    w0, h0 = nndsvd_init(jnp.asarray(a, jnp.float64), 3,
                         dtype=jnp.float64)
    assert w0.dtype == jnp.float64
    np.testing.assert_allclose(np.asarray(w0), w_ref, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(h0), h_ref, rtol=1e-10)
    print("OK")
    """)
