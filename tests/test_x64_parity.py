"""f64 parity mode in a dedicated subprocess (jax_enable_x64 is global and
must be set before any JAX use, so the in-process suite can only skip it —
SolverConfig.dtype='float64' is the documented parity path vs the
reference's f64 BLAS)."""

import subprocess
import sys
import textwrap


def test_f64_solver_runs_in_subprocess():
    code = textwrap.dedent("""
        import jax
        jax.config.update("jax_enable_x64", True)
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import jax.numpy as jnp
        from nmfx.config import SolverConfig
        from nmfx.solvers import solve

        rng = np.random.default_rng(0)
        a = rng.uniform(0.1, 1.0, (60, 22))
        w0 = rng.uniform(0.1, 1.0, (60, 3))
        h0 = rng.uniform(0.1, 1.0, (3, 22))
        res = solve(a, w0, h0, SolverConfig(algorithm="mu", max_iter=25,
                                            dtype="float64",
                                            use_class_stop=False,
                                            use_tol_checks=False))
        assert res.w.dtype == jnp.float64, res.w.dtype

        # lockstep vs the identical update in NumPy f64: agreement must be
        # at f64 level, far beyond anything f32 could produce
        w, h = np.asarray(w0, np.float64), np.asarray(h0, np.float64)
        for _ in range(25):
            numerh = w.T @ a
            hn = h * numerh / ((w.T @ w) @ h + 1e-9)
            hn[(h == 0) | (numerh == 0)] = 0.0
            h = hn
            numerw = a @ h.T
            wn = w * numerw / (w @ (h @ h.T) + 1e-9)
            wn[(w == 0) | (numerw == 0)] = 0.0
            w = wn
        np.testing.assert_allclose(np.asarray(res.w), w, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(res.h), h, rtol=1e-10)
        print("OK")
    """)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
