"""The scheduler's slot-pool clamps (nmfx.ops.sched_mu).

Pure-arithmetic tests on the two memory models that size the pool: the
pallas resident-W VMEM envelope (byte model calibrated on-chip in round
4 — these tests pin the measured boundary points so a formula edit that
shifts the envelope fails loudly) and the kl quotient clamp (the
grid_slots-as-restart_chunk memory bound).
"""

import logging

import jax.numpy as jnp
import pytest

from nmfx.config import SolverConfig
from nmfx.ops.sched_mu import _kl_slot_clamp, _pallas_slot_clamp

BF16 = SolverConfig(matmul_precision="bfloat16")


def pallas_clamp(s, k_max, m, n, cfg=BF16):
    return _pallas_slot_clamp(s, k_max, m, n, cfg)


def test_pallas_envelope_measured_boundaries(monkeypatch):
    """The fitted byte model must reproduce the on-chip OK/OOM points
    (benchmarks/probe_vmem_envelope*.py): rk=480 fits at the north star,
    rk=512 does not; rk=384 overflows at n=1024 while 320 fits."""
    import nmfx.ops.sched_mu as sm

    # the a_bytes predicate consults jax.default_backend(); pin the
    # TPU-streaming answer so the test is platform-free
    monkeypatch.setattr(sm, "_streams_bf16_a", lambda cfg: True)
    # north star: k_max=10, 48 requested -> 48 kept (rk=480 measured OK)
    assert pallas_clamp(48, 10, 5000, 500) == 48
    # rk=512 measured OOM: k_max=8 at 64 requested must clamp below 64
    assert pallas_clamp(64, 8, 5000, 500) < 64
    # n=1024: rk=384 OOM, rk=320 OK -> clamp for k_max=32 lands in [10, 11]
    c = pallas_clamp(48, 32, 5000, 1024)
    assert 10 <= c <= 11
    # a single job beyond the envelope is a clear error
    with pytest.raises(ValueError, match="VMEM envelope"):
        pallas_clamp(1, 600, 20000, 2048)


def test_pallas_clamp_logs_reduction(monkeypatch, caplog):
    import nmfx.ops.sched_mu as sm

    monkeypatch.setattr(sm, "_streams_bf16_a", lambda cfg: True)
    with caplog.at_level(logging.WARNING, logger="nmfx"):
        pallas_clamp(64, 8, 5000, 500)
    assert any("slot pool clamped" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="nmfx"):
        pallas_clamp(48, 10, 5000, 500)  # fits: silent
    assert not caplog.records


def test_kl_clamp_bounds_quotient_memory(caplog):
    # north star: 133-slot ceiling -> 48 untouched
    assert _kl_slot_clamp(48, 5000, 500, jnp.float32) == 48
    # 20000x1000 f32: 3*80 MB per lane -> 16 slots
    with caplog.at_level(logging.WARNING, logger="nmfx"):
        assert _kl_slot_clamp(48, 20000, 1000, jnp.float32) == 16
    assert any("kl scheduler" in r.message for r in caplog.records)
    # never below one slot
    assert _kl_slot_clamp(4, 200000, 10000, jnp.float32) == 1
