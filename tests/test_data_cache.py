"""Device-resident input cache (ISSUE 5): repeat sweeps over the same
matrix transfer zero bytes, gated by the module transfer counters (the
honesty-counter discipline of ``exec_cache.compile_count()``); the
content-fingerprint key discriminates everything that changes the
device buffer; the LRU bounds live-buffer memory."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx import data_cache
from nmfx.config import SolverConfig
from nmfx.data_cache import DataCache, DataKey, data_key_fields

SCFG = SolverConfig()


def _matrix(seed=0, shape=(40, 12)):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.1, 1.0, size=shape)


def test_repeat_place_is_zero_transfer():
    """THE contract: the second placement of the same content serves the
    resident buffer — counters unchanged, same device array back."""
    cache = DataCache()
    a = _matrix(0)
    t0, b0 = data_cache.transfer_count(), data_cache.h2d_bytes()
    x1 = cache.place(a, SCFG)
    t1, b1 = data_cache.transfer_count(), data_cache.h2d_bytes()
    assert t1 == t0 + 1 and b1 > b0
    x2 = cache.place(a, SCFG)
    assert x2 is x1
    assert data_cache.transfer_count() == t1
    assert data_cache.h2d_bytes() == b1
    assert cache.stats["hits"] == 1 and cache.stats["misses"] == 1
    np.testing.assert_array_equal(
        np.asarray(x1), np.asarray(a, np.asarray(x1).dtype))


def test_content_fingerprint_not_identity():
    """An equal-content COPY hits; an in-place mutation misses — the
    honesty discipline: the key is the bytes, not the object."""
    cache = DataCache()
    a = _matrix(1)
    x1 = cache.place(a, SCFG)
    t = data_cache.transfer_count()
    assert cache.place(a.copy(), SCFG) is x1  # same bytes, zero transfer
    assert data_cache.transfer_count() == t
    a[0, 0] += 1.0  # caller mutates: must NOT see the stale buffer
    x3 = cache.place(a, SCFG)
    assert x3 is not x1
    assert data_cache.transfer_count() == t + 1
    assert float(np.asarray(x3)[0, 0]) == pytest.approx(float(a[0, 0]))


def test_key_discriminates_placement():
    """Same content under a different dtype or pad shape is a different
    buffer — every DataKey field separates entries."""
    cache = DataCache()
    a = _matrix(2)
    base = cache.place(a, SCFG)
    padded = cache.place(a, SCFG, pad_shape=(64, 16))
    assert padded.shape == (64, 16)
    assert padded is not base
    m, n = a.shape
    np.testing.assert_array_equal(np.asarray(padded)[:m, :n],
                                  np.asarray(base))
    assert np.asarray(padded)[m:, :].sum() == 0
    # a different placement dtype is a different key (even where the
    # backend canonicalizes the buffer dtype, e.g. x64 disabled)
    other_dtype = cache.place(a, SolverConfig(dtype="float64"))
    assert other_dtype is not base
    assert cache.stats["misses"] == 3
    # and each repeat is a hit
    assert cache.place(a, SCFG, pad_shape=(64, 16)) is padded
    assert cache.stats["hits"] == 1


def test_device_array_passthrough_not_cached():
    """A jax.Array input is already resident: no fingerprint round trip,
    no counter movement, no cache entry."""
    cache = DataCache()
    a_dev = jnp.asarray(_matrix(3), jnp.float32)
    t = data_cache.transfer_count()
    out = cache.place(a_dev, SCFG)
    assert data_cache.transfer_count() == t
    assert cache.stats["entries"] == 0
    np.testing.assert_array_equal(np.asarray(out), np.asarray(a_dev))
    padded = cache.place(a_dev, SCFG, pad_shape=(64, 16))
    assert padded.shape == (64, 16)
    assert cache.stats["entries"] == 0


def test_lru_entry_bound():
    cache = DataCache(max_entries=2)
    first = _matrix(10)
    cache.place(first, SCFG)
    cache.place(_matrix(11), SCFG)
    cache.place(_matrix(12), SCFG)  # evicts the LRU (first)
    assert cache.stats["entries"] == 2
    assert cache.stats["evictions"] == 1
    t = data_cache.transfer_count()
    cache.place(first, SCFG)  # evicted: a fresh transfer
    assert data_cache.transfer_count() == t + 1


def test_byte_bound_and_oversized_not_retained():
    a = _matrix(13)
    nbytes = a.shape[0] * a.shape[1] * 4  # float32 placement
    cache = DataCache(max_bytes=nbytes - 1)
    out = cache.place(a, SCFG)  # transferred but too big to retain
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(a, np.float32))
    assert cache.stats["entries"] == 0


def test_chunked_put_bitwise_equal():
    """The double-buffered first touch (row-chunked async device_put)
    reassembles the exact array."""
    rows = 2200  # 2200 x 1024 f32 ~ 9 MB > _CHUNK_MIN_BYTES
    host = np.arange(rows * 1024, dtype=np.float32).reshape(rows, 1024)
    out = DataCache._chunked_put(host)
    assert out.shape == host.shape
    np.testing.assert_array_equal(np.asarray(out), host)


def test_data_key_fields_cover_every_field():
    """The NMFX001 hook: every DataKey field participates in the cache
    key (compare=True). A compare=False field would alias two
    placements onto one buffer — lint fails before this test does."""
    assert data_key_fields() == frozenset(
        f.name for f in dataclasses.fields(DataKey))
    assert {"fingerprint", "src_dtype", "shape", "dtype", "pad_shape",
            "mesh", "device"} <= data_key_fields()


def test_byte_view_aliasing_rejected():
    """Same raw bytes under a different source dtype are different
    VALUES: a float32 matrix and its int32 byte-view must not share a
    buffer (the key carries src_dtype, not just the content hash)."""
    cache = DataCache()
    a = np.asarray([[1.0, 2.0], [3.0, 4.0]], dtype=np.float32)
    b = a.view(np.int32).copy()  # identical bytes, different values
    x = cache.place(a, SCFG)
    y = cache.place(b, SCFG)
    assert y is not x
    np.testing.assert_array_equal(np.asarray(y),
                                  b.astype(np.float32))


def test_second_sweep_zero_h2d():
    """End to end through the DEFAULT path (the acceptance gate): the
    second ``sweep()`` over the same array records zero h2d transfers
    and zero bytes."""
    from nmfx.config import ConsensusConfig
    from nmfx.sweep import sweep

    a = _matrix(20, shape=(60, 20))
    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=5)
    scfg = SolverConfig(max_iter=20)
    out1 = sweep(a, ccfg, scfg)
    jax.block_until_ready(out1[2].consensus)
    t, b = data_cache.transfer_count(), data_cache.h2d_bytes()
    out2 = sweep(a, ccfg, scfg)
    jax.block_until_ready(out2[2].consensus)
    assert data_cache.transfer_count() == t, "second sweep paid a transfer"
    assert data_cache.h2d_bytes() == b, "second sweep paid h2d bytes"
    np.testing.assert_array_equal(np.asarray(out1[2].consensus),
                                  np.asarray(out2[2].consensus))


def test_profiler_sees_hit_and_miss_phases():
    from nmfx.profiling import Profiler

    cache = DataCache()
    a = _matrix(30)
    prof = Profiler()
    cache.place(a, SCFG, profiler=prof)
    assert prof.phases["xfer.h2d_overlap"].count == 1
    cache.place(a, SCFG, profiler=prof)
    assert prof.phases["xfer.h2d_cache_hit"].count == 1
    # both are overlap-classed: they never inflate the sequential
    # phase-sum the audit reconciles against the wall
    assert all(prof.phases[n].overlapped for n in prof.phases)


def test_resize_evicts_and_disables():
    """The runtime sizing surface (CLI --input-cache-bytes): shrinking
    evicts LRU-first; max_bytes=0 retains nothing but still places
    correctly."""
    cache = DataCache(max_entries=4)
    a, b = _matrix(40), _matrix(41)
    cache.place(a, SCFG)
    cache.place(b, SCFG)
    assert cache.stats["entries"] == 2
    nbytes_one = a.size * 4  # float32 placement
    cache.resize(max_bytes=nbytes_one)  # room for ONE entry: a evicted
    assert cache.stats["entries"] == 1
    assert cache.place(b, SCFG) is not None
    assert cache.stats["hits"] == 1  # b survived as the MRU entry
    cache.resize(max_bytes=0)
    assert cache.stats["entries"] == 0
    out = cache.place(a, SCFG)  # transfers, retains nothing
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(a, np.float32))
    assert cache.stats["entries"] == 0


def test_validation():
    with pytest.raises(ValueError):
        DataCache(max_entries=0)
    with pytest.raises(ValueError):
        DataCache(max_bytes=-1)
    with pytest.raises(ValueError):
        DataCache().resize(max_entries=0)
    with pytest.raises(ValueError):
        DataCache().resize(max_bytes=-1)


def test_concurrent_place_access():
    """ISSUE 6 satellite: the serve front-end's submit threads and
    scheduler share one DataCache. Under concurrent hammering from
    many threads over a mixed hot/cold key set, the counters must
    balance exactly (hits + misses == host-path place calls — the
    lookup-or-miss decision and its counter land in one lock
    acquisition), every returned buffer must hold the right values,
    and the entry table must stay within bounds. Two threads racing
    the same cold key may both transfer (by design — the transfer runs
    outside the lock so it can overlap other threads' hits): that
    shows up as extra honest misses, never a corrupt entry."""
    import threading

    cache = DataCache(max_entries=8)
    mats = [_matrix(seed) for seed in range(4)]
    calls_per_thread = 12
    n_threads = 8
    errors = []

    def worker(tid):
        try:
            for i in range(calls_per_thread):
                a = mats[(tid + i) % len(mats)]
                out = cache.place(a, SCFG)
                np.testing.assert_array_equal(
                    np.asarray(out), np.asarray(a, np.float32))
        except Exception as e:  # surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    s = cache.stats
    assert s["hits"] + s["misses"] == n_threads * calls_per_thread
    # at least one miss per distinct key; races may add more, but every
    # surplus miss is an honest recorded transfer, never a lost count
    assert s["misses"] >= len(mats)
    assert s["entries"] <= len(mats)
