"""Result cache (ISSUE 16 tentpole): content-addressed finished-result
reuse — key sensitivity, the two-tier LRU store, and the
zero-dispatch/zero-h2d warm-hit contract.

Key EXHAUSTIVENESS (every covered field, including future ones) is
lint rule NMFX011's job (tests/test_lint_rules.py): the rule
cross-references ``cache_key_fields()`` against the live dataclasses,
so a new result-affecting field can never silently drop out of the
key. The sensitivity tests here pin the *mechanism* on representative
fields from each key component — data identity, solver numerics,
consensus policy, init, quality."""

import dataclasses
import os
import warnings

import numpy as np
import pytest

import nmfx.serve as serve_mod
from nmfx import data_cache
from nmfx.api import nmfconsensus
from nmfx.config import (ConsensusConfig, InitConfig, ResultCacheConfig,
                         SolverConfig)
from nmfx.result_cache import (ResultCache, cache_key_fields, cacheable,
                               key_for_array, request_quality, result_key)
from nmfx.serve import NMFXServer, ServeConfig

KW = dict(ks=(2,), restarts=2, seed=5)


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=60, n_per_group=10, seed=7)


@pytest.fixture(scope="module")
def tiny_result(small_data):
    """One real finished ConsensusResult the store tests re-address."""
    return nmfconsensus(small_data, solver_cfg=SolverConfig(max_iter=20),
                        use_mesh=False, **KW)


def _bit_identical(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            a = np.ascontiguousarray(np.asarray(getattr(got.per_k[k],
                                                        field)))
            b = np.ascontiguousarray(np.asarray(getattr(ref.per_k[k],
                                                        field)))
            assert a.shape == b.shape and a.dtype == b.dtype \
                and a.tobytes() == b.tobytes(), f"{field} k={k}"
        assert got.per_k[k].rho == ref.per_k[k].rho


# ---------------------------------------------------------------------
# the key: content + config + quality sensitivity
# ---------------------------------------------------------------------

def test_key_covers_declared_fields():
    cov = cache_key_fields()
    # the consensus side keys EVERYTHING (RESULT_CACHE_EXEMPT_FIELDS is
    # deliberately empty — the checkpoint/result-cache asymmetry): a
    # finished restarts=4 answer is not a restarts=8 answer
    assert cov["consensus"] == frozenset(
        f.name for f in dataclasses.fields(ConsensusConfig))
    assert {"restarts", "ks", "seed", "linkage"} <= cov["consensus"]
    # the solver side is the checkpoint manifest's numerics coverage
    assert "algorithm" in cov["solver"]
    assert "restart_chunk" not in cov["solver"]  # execution-only


def test_key_sensitive_to_every_component():
    base = result_key("fp0", (8, 6), "<f4")
    seen = {base}

    def differs(**kw):
        args = dict(fingerprint="fp0", shape=(8, 6), src_dtype="<f4")
        args.update(kw)
        k = result_key(args.pop("fingerprint"), args.pop("shape"),
                       args.pop("src_dtype"), **args)
        assert k not in seen, f"key collision for {kw}"
        seen.add(k)

    differs(fingerprint="fp1")               # different content
    differs(shape=(6, 8))                    # same bytes, other shape
    differs(src_dtype="<f8")                 # same bytes, other dtype
    differs(scfg=SolverConfig(algorithm="hals"))
    differs(scfg=SolverConfig(max_iter=17))
    differs(scfg=SolverConfig(dtype="bfloat16"))
    differs(ccfg=ConsensusConfig(restarts=3))
    differs(ccfg=ConsensusConfig(ks=(2, 3)))
    differs(ccfg=ConsensusConfig(seed=1))
    differs(ccfg=ConsensusConfig(linkage="complete"))
    differs(icfg=InitConfig(method="nndsvd"))
    differs(quality="sketched")              # quality separation


def test_key_insensitive_to_execution_strategy():
    """NON_NUMERICS_FIELDS change scheduling, never numbers — two runs
    differing only in them share one finished result."""
    base = result_key("fp0", (8, 6), "<f4")
    assert result_key("fp0", (8, 6), "<f4",
                      scfg=SolverConfig(restart_chunk=3)) == base


def test_key_for_array_matches_content_not_object():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    assert key_for_array(a) == key_for_array(a.copy())
    assert key_for_array(a) != key_for_array(a + 1)
    # a transposed view has the same bytes under ascontiguousarray
    # normalization only if shape matches — it must NOT collide
    assert key_for_array(a) != key_for_array(a.T)


def test_request_quality_tags():
    assert request_quality(SolverConfig()) == "exact"
    assert request_quality(
        SolverConfig(backend="sketched")) == "sketched"


def test_cacheable_rejects_keep_factors():
    assert cacheable(ConsensusConfig())
    assert not cacheable(ConsensusConfig(keep_factors=True))


# ---------------------------------------------------------------------
# the store: memory LRU over the atomic disk tier
# ---------------------------------------------------------------------

def test_memory_lru_bound_and_stats(tiny_result):
    rc = ResultCache(ResultCacheConfig(max_entries=2))
    for key in ("k1", "k2", "k3"):
        assert rc.put(key, tiny_result)
    assert len(rc) == 2
    assert rc.stats["mem_evictions"] == 1
    assert rc.lookup("k1") is None          # the oldest was evicted
    assert rc.lookup("k3") is tiny_result   # memory hit: same object
    assert rc.stats["hits"] == 1 and rc.stats["misses"] == 1


def test_lru_get_refreshes_recency(tiny_result):
    rc = ResultCache(ResultCacheConfig(max_entries=2))
    rc.put("k1", tiny_result)
    rc.put("k2", tiny_result)
    rc.lookup("k1")                 # touch: k2 becomes the eviction victim
    rc.put("k3", tiny_result)
    assert rc.lookup("k1") is not None and rc.lookup("k2") is None


def test_disk_roundtrip_fresh_instance(tiny_result, tmp_path):
    key = "a" * 64
    ResultCache(cache_dir=str(tmp_path)).put(key, tiny_result)
    entries = [n for n in os.listdir(tmp_path) if n.endswith(".nmfxres")]
    assert len(entries) == 1 and not any(
        n.endswith(".part") for n in os.listdir(tmp_path))
    fresh = ResultCache(cache_dir=str(tmp_path))
    got = fresh.lookup(key)
    assert got is not None and fresh.stats["hits"] == 1
    _bit_identical(got, tiny_result)
    # the disk hit was re-admitted to memory: second get is a mem hit
    assert fresh.lookup(key) is got


def test_corrupt_entry_dropped_warn_once(tiny_result, tmp_path):
    key = "b" * 64
    rc = ResultCache(cache_dir=str(tmp_path))
    rc.put(key, tiny_result)
    path = os.path.join(str(tmp_path), key[:40] + ".nmfxres")
    with open(path, "wb") as f:
        f.write(b"not a zip at all")
    fresh = ResultCache(cache_dir=str(tmp_path))
    with pytest.warns(RuntimeWarning, match="result cache"):
        assert fresh.lookup(key) is None
    assert not os.path.exists(path)  # unusable entry was dropped
    # warn ONCE per category: a second corrupt read stays quiet
    rc.put(key, tiny_result)
    with open(path, "wb") as f:
        f.write(b"garbage again")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert fresh.lookup(key) is None


def test_key_mismatched_entry_never_served(tiny_result, tmp_path):
    """An entry renamed onto another key's path (or a hash-prefix
    collision) fails the embedded verification record — a miss, never a
    wrong result."""
    k1, k2 = "c" * 64, "c" * 40 + "d" * 24  # same 40-char disk prefix
    rc = ResultCache(cache_dir=str(tmp_path))
    rc.put(k1, tiny_result)
    fresh = ResultCache(cache_dir=str(tmp_path))
    with pytest.warns(RuntimeWarning, match="result cache"):
        assert fresh.lookup(k2) is None


def test_disk_byte_cap_evicts_oldest(tiny_result, tmp_path):
    rc = ResultCache(ResultCacheConfig(cache_dir=str(tmp_path),
                                       max_disk_bytes=1))
    rc.put("d" * 64, tiny_result)
    rc.put("e" * 64, tiny_result)
    entries = [n for n in os.listdir(tmp_path) if n.endswith(".nmfxres")]
    # the cap admits the JUST-written entry even when it alone exceeds
    # it, evicting the older one
    assert entries == ["e" * 40 + ".nmfxres"]
    assert rc.stats["disk_evictions"] >= 1


def test_keep_factors_result_refused(small_data, tmp_path):
    res = nmfconsensus(small_data, solver_cfg=SolverConfig(max_iter=10),
                       keep_factors=True, use_mesh=False, **KW)
    rc = ResultCache(cache_dir=str(tmp_path))
    assert not rc.put("f" * 64, res)                 # retained stacks
    assert not rc.put("f" * 64, res,
                      ccfg=ConsensusConfig(keep_factors=True))
    assert rc.lookup("f" * 64) is None
    assert os.listdir(tmp_path) == []


# ---------------------------------------------------------------------
# the serving contract: warm hit = zero dispatches, zero h2d
# ---------------------------------------------------------------------

def test_serve_warm_hit_zero_dispatch_zero_h2d(small_data, tmp_path):
    scfg = SolverConfig(max_iter=20)
    cfg = ServeConfig(result_cache_dir=str(tmp_path))
    with NMFXServer(cfg) as srv:
        ref = srv.submit(small_data, solver_cfg=scfg,
                         **KW).result(timeout=240)
        d0 = serve_mod.dispatch_count()
        t0 = data_cache.transfer_count()
        b0 = data_cache.h2d_bytes()
        got = srv.submit(small_data, solver_cfg=scfg,
                         **KW).result(timeout=240)
        st = srv.stats()
    assert serve_mod.dispatch_count() == d0   # ZERO solve dispatches
    assert data_cache.transfer_count() == t0  # ZERO h2d transfers
    assert data_cache.h2d_bytes() == b0
    assert st["result_cache_hits"] == 1
    assert st["submitted"] == 2 and st["completed"] == 2
    _bit_identical(got, ref)


def test_serve_warm_hit_across_server_instances(small_data, tmp_path):
    """The disk tier carries results across processes/servers: a FRESH
    server over the same directory hits without solving."""
    scfg = SolverConfig(max_iter=20)
    cfg = ServeConfig(result_cache_dir=str(tmp_path))
    with NMFXServer(cfg) as srv:
        ref = srv.submit(small_data, solver_cfg=scfg,
                         **KW).result(timeout=240)
    d0 = serve_mod.dispatch_count()
    with NMFXServer(cfg) as srv2:
        got = srv2.submit(small_data, solver_cfg=scfg,
                          **KW).result(timeout=240)
        assert srv2.stats()["result_cache_hits"] == 1
    assert serve_mod.dispatch_count() == d0
    _bit_identical(got, ref)


def test_serve_config_change_misses(small_data, tmp_path):
    """A different seed must MISS — no stale serve across configs."""
    cfg = ServeConfig(result_cache_dir=str(tmp_path))
    scfg = SolverConfig(max_iter=20)
    with NMFXServer(cfg) as srv:
        srv.submit(small_data, solver_cfg=scfg, **KW).result(timeout=240)
        d0 = serve_mod.dispatch_count()
        srv.submit(small_data, solver_cfg=scfg,
                   **dict(KW, seed=6)).result(timeout=240)
        st = srv.stats()
    assert serve_mod.dispatch_count() > d0    # it really solved
    assert st["result_cache_hits"] == 0


def test_deadline_requests_bypass_cache(small_data, tmp_path):
    """A deadline'd request is ineligible (a replayed result cannot
    honor a latency contract it never saw): it solves, and does not
    count as a hit."""
    cfg = ServeConfig(result_cache_dir=str(tmp_path))
    scfg = SolverConfig(max_iter=20)
    with NMFXServer(cfg) as srv:
        srv.submit(small_data, solver_cfg=scfg, **KW).result(timeout=240)
        d0 = serve_mod.dispatch_count()
        srv.submit(small_data, solver_cfg=scfg, timeout=240.0,
                   **KW).result(timeout=240)
        st = srv.stats()
    assert serve_mod.dispatch_count() > d0
    assert st["result_cache_hits"] == 0  # never even looked up


def test_api_result_cache_roundtrip(small_data, tmp_path):
    rc = ResultCache(cache_dir=str(tmp_path), layer="api")
    scfg = SolverConfig(max_iter=20)
    ref = nmfconsensus(small_data, solver_cfg=scfg, use_mesh=False,
                       result_cache=rc, **KW)
    assert rc.stats["misses"] == 1 and rc.stats["puts"] == 1
    got = nmfconsensus(small_data, solver_cfg=scfg, use_mesh=False,
                       result_cache=rc, **KW)
    assert rc.stats["hits"] == 1
    _bit_identical(got, ref)
