"""End-to-end pipeline tests: the full consensus sweep on synthetic designs
(SURVEY.md §4: cophenetic rho must peak at the planted number of groups)."""

import os

import numpy as np
import pytest

from nmfx.api import nmfconsensus, save_results
from nmfx.config import OutputConfig, SolverConfig
from nmfx.datasets import grouped_matrix


@pytest.fixture(scope="module")
def two_group_result(two_group_data):
    return nmfconsensus(two_group_data, ks=(2, 3, 4), restarts=8, seed=123,
                        max_iter=2000)


# session-scope fixture lives in conftest; re-export at module scope
@pytest.fixture(scope="module")
def two_group_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=120, n_per_group=12, seed=7)


def test_rho_peaks_at_two_groups(two_group_result):
    res = two_group_result
    assert res.best_k == 2, f"rhos: {dict(zip(res.ks, res.rhos))}"
    assert res.per_k[2].rho > 0.9


def test_membership_recovers_groups(two_group_result):
    m = two_group_result.per_k[2].membership
    # the planted design is samples [0:12] vs [12:24]
    g1, g2 = set(m[:12]), set(m[12:])
    assert len(g1) == 1 and len(g2) == 1 and g1 != g2


def test_result_shapes(two_group_result):
    res = two_group_result
    n = 24
    for k in res.ks:
        r = res.per_k[k]
        assert r.consensus.shape == (n, n)
        assert r.membership.shape == (n,)
        assert sorted(r.order.tolist()) == list(range(n))
        assert r.iterations.shape == (8,)
        np.testing.assert_allclose(np.diag(r.consensus), 1.0)
        assert r.consensus.min() >= 0 and r.consensus.max() <= 1.0 + 1e-6


def test_three_groups():
    a = grouped_matrix(150, (10, 10, 10), effect=2.5, seed=11)
    res = nmfconsensus(a, ks=(2, 3, 4, 5), restarts=6, seed=1, max_iter=1500)
    assert res.per_k[3].rho > 0.85
    assert res.best_k in (2, 3)  # k=2 can tie when two blocks merge cleanly


def test_reproducible(two_group_data):
    r1 = nmfconsensus(two_group_data, ks=(2,), restarts=4, seed=9,
                      max_iter=500)
    r2 = nmfconsensus(two_group_data, ks=(2,), restarts=4, seed=9,
                      max_iter=500)
    np.testing.assert_array_equal(r1.per_k[2].consensus,
                                  r2.per_k[2].consensus)
    # a different seed gives different factorizations (consensus may coincide
    # on a clean design, so compare per-restart residuals)
    r3 = nmfconsensus(two_group_data, ks=(2,), restarts=4, seed=10,
                      max_iter=500)
    assert not np.array_equal(r1.per_k[2].dnorms, r3.per_k[2].dnorms)


def test_save_results(two_group_result, tmp_path):
    out = OutputConfig(directory=str(tmp_path), write_plots=False)
    written = save_results(two_group_result, out)
    for path in written:
        assert os.path.exists(path), path
    assert any(p.endswith("cophenetic.txt") for p in written)
    assert any(p.endswith("membership.gct") for p in written)
    metrics = [p for p in written if p.endswith("rank_metrics.txt")][0]
    lines = open(metrics).read().splitlines()
    assert lines[0].split("\t") == ["k", "rho", "dispersion", "mean_iters",
                                    "mean_dnorm"]
    assert len(lines) == 1 + len(two_group_result.ks)
    meta = [p for p in written if p.endswith("metagenes.k.2.gct")]
    assert meta
    from nmfx.io import read_gct

    ds = read_gct(meta[0])
    assert ds.values.shape == (2, len(two_group_result.col_names))
    np.testing.assert_allclose(ds.values,
                               two_group_result.per_k[2].best_h, rtol=1e-6)


@pytest.mark.slow
def test_per_k_results_independent_of_sweep_composition(two_group_data):
    # (seed, k) fully determines a rank's factorizations, no matter which
    # other ranks are swept alongside it. Under per_k execution this is
    # bit-exact; under whole-grid execution the same initial factors solve
    # inside one shared batch, so other ranks' lanes change GEMM reduction
    # grouping and the guarantee is float-tolerance (ConsensusConfig.
    # grid_exec) — the solo run takes the per-k path either way (one rank).
    solo = nmfconsensus(two_group_data, ks=(3,), restarts=4, seed=5,
                        max_iter=400)
    per_k = nmfconsensus(two_group_data, ks=(2, 3), restarts=4, seed=5,
                         max_iter=400, grid_exec="per_k")
    np.testing.assert_array_equal(per_k.per_k[3].dnorms,
                                  solo.per_k[3].dnorms)
    grid = nmfconsensus(two_group_data, ks=(2, 3), restarts=4, seed=5,
                        max_iter=400, grid_exec="grid")
    np.testing.assert_allclose(grid.per_k[3].dnorms, solo.per_k[3].dnorms,
                               rtol=1e-5)


def test_conflicting_cfg_and_args_rejected(two_group_data):
    with pytest.raises(ValueError, match="solver_cfg"):
        nmfconsensus(two_group_data, ks=(2,), restarts=2, algorithm="als",
                     solver_cfg=SolverConfig(max_iter=50))
    with pytest.raises(ValueError, match="init"):
        nmfconsensus(two_group_data, ks=(2,), restarts=2, init="nndsvd",
                     init_cfg=__import__("nmfx").InitConfig())


def test_best_factors_retained(two_group_result):
    r = two_group_result.per_k[2]
    assert r.best_w.shape == (120, 2)
    assert r.best_h.shape == (2, 24)
    assert (r.best_w >= 0).all() and (r.best_h >= 0).all()


def test_negative_input_rejected():
    a = np.full((4, 4), -1.0)
    with pytest.raises(ValueError):
        nmfconsensus(a, ks=(2,), restarts=2)


def test_k_below_two_rejected(two_group_data):
    # reference guard: nmf.r:107-108
    with pytest.raises(ValueError):
        nmfconsensus(two_group_data, ks=(1, 2), restarts=2)


def test_dispersion_metric(two_group_result):
    """Kim & Park dispersion: 1.0 iff the consensus is crisp (all 0/1);
    the clean two-group design at k=2 should be essentially crisp, and
    every k's value must lie in (0, 1]."""
    res = two_group_result
    d = res.dispersions
    assert d.shape == (3,)
    assert np.all(d > 0) and np.all(d <= 1.0 + 1e-12)
    assert res.per_k[2].dispersion > 0.95
    # hand-check the definition on one consensus matrix
    c = res.per_k[3].consensus
    np.testing.assert_allclose(res.per_k[3].dispersion,
                               np.mean((2 * c - 1) ** 2))
    assert "dispersion" in res.summary().splitlines()[0]


def test_standalone_plots(two_group_data, two_group_result, tmp_path):
    """matrix_plot / pca_plot (reference matrix.abs.plot and the never-wired
    plotPCA, test_nmf.r:9-23) write valid files."""
    from nmfx import plots

    p1 = tmp_path / "mat.pdf"
    plots.matrix_plot(two_group_data, str(p1), title="A")
    p2 = tmp_path / "pca.pdf"
    plots.pca_plot(two_group_data, str(p2),
                   labels=two_group_result.per_k[2].membership)
    p3 = tmp_path / "pca_nolabels.pdf"
    plots.pca_plot(two_group_data, str(p3))
    for p in (p1, p2, p3):
        assert p.exists() and p.stat().st_size > 500


def test_k_exceeding_samples_rejected(two_group_data):
    n = two_group_data.shape[1]
    with pytest.raises(ValueError, match="exceeds the number of samples"):
        nmfconsensus(two_group_data, ks=(2, n + 1), restarts=2,
                     max_iter=20, use_mesh=False)


def test_nonfinite_input_rejected(two_group_data):
    from nmfx.api import nmf

    bad = np.array(two_group_data, copy=True)
    bad[0, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        nmfconsensus(bad, ks=(2,), restarts=2, max_iter=20, use_mesh=False)
    with pytest.raises(ValueError, match="non-finite"):
        nmf(bad, k=2)


def test_result_save_load_roundtrip(two_group_result, tmp_path):
    from nmfx.api import ConsensusResult, KResult
    import dataclasses

    path = str(tmp_path / "result.npz")
    two_group_result.save(path)
    loaded = ConsensusResult.load(path)
    assert loaded.ks == two_group_result.ks
    assert loaded.col_names == two_group_result.col_names
    assert loaded.best_k == two_group_result.best_k
    for k in loaded.ks:
        a, b = loaded.per_k[k], two_group_result.per_k[k]
        for f in dataclasses.fields(KResult):
            got, ref = getattr(a, f.name), getattr(b, f.name)
            if isinstance(ref, np.ndarray):
                np.testing.assert_array_equal(got, ref)
            else:
                assert got == ref and type(got) is type(ref)
    assert loaded.summary() == two_group_result.summary()
    # extensionless path: save/load stay symmetric (savez would append .npz)
    bare = str(tmp_path / "result_bare")
    two_group_result.save(bare)
    assert ConsensusResult.load(bare).best_k == two_group_result.best_k


def test_reference_dataset_end_to_end():
    """Full pipeline on the reference's own bundled fixture (1000 genes x
    40 samples, two 20-sample groups — the filename encodes the design):
    rho must peak at k=2 and the k=2 membership must split the two groups
    exactly (reference runExample's data, nmf.r:11)."""
    path = os.environ.get("NMFX_REFERENCE_GCT",
                          "/root/reference/20+20x1000.gct")
    if not os.path.exists(path):
        pytest.skip(f"reference fixture not found at {path} "
                    "(set NMFX_REFERENCE_GCT)")
    res = nmfconsensus(path, ks=(2, 3), restarts=6, seed=123, max_iter=800,
                       use_mesh=False)
    assert res.best_k == 2
    assert res.per_k[2].rho >= 0.99
    m = res.per_k[2].membership
    assert len(set(m[:20])) == 1 and len(set(m[20:])) == 1
    assert m[0] != m[20]


def test_run_example():
    """nmfx.run_example mirrors the reference's runExample (nmf.r:6-14) on
    the equivalent synthetic design; shrunk here via kwargs for test speed."""
    import nmfx

    res = nmfx.run_example(outdir=None, ks=(2, 3), restarts=4, max_iter=300,
                           use_mesh=False)
    assert res.best_k == 2


def test_nmf_warm_start(two_group_data):
    from nmfx.api import nmf

    a = two_group_data
    first = nmf(a, k=2, max_iter=100, seed=1)
    warm = nmf(a, k=2, max_iter=50, w0=np.asarray(first.w),
               h0=np.asarray(first.h))
    assert float(warm.dnorm) <= float(first.dnorm) + 1e-5
    with pytest.raises(ValueError, match="both w0 and h0"):
        nmf(a, k=2, w0=np.asarray(first.w))
    with pytest.raises(ValueError, match="shapes"):
        nmf(a, k=2, w0=np.ones((3, 2)), h0=np.ones((2, 3)))
    with pytest.raises(ValueError, match="non-negative"):
        nmf(a, k=2, w0=-np.asarray(first.w), h0=np.asarray(first.h))
    bad = np.array(first.w, copy=True)
    bad[0, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        nmf(a, k=2, w0=bad, h0=np.asarray(first.h))
    with pytest.raises(ValueError, match="not both"):
        nmf(a, k=2, init="nndsvd", w0=np.asarray(first.w),
            h0=np.asarray(first.h))


def test_save_results_with_plots(two_group_result, tmp_path):
    """write_plots=True: the full artifact set incl. every PDF (per-k
    consensus heatmaps, all-k grid, cophenetic curve, metagene plots) —
    the reference's plotting outputs (nmf.r:191-249)."""
    out = OutputConfig(directory=str(tmp_path))
    written = save_results(two_group_result, out)
    pdfs = [p for p in written if p.endswith(".pdf")]
    names = {os.path.basename(p) for p in pdfs}
    assert "consensus.all.k.plot.pdf" in names
    assert "cophenetic.plot.pdf" in names
    for k in two_group_result.ks:
        assert f"consensus.plot.k{k}.pdf" in names
        assert f"metagenes.k{k}.pdf" in names
    for p in written:
        assert os.path.getsize(p) > 20, p
    for p in pdfs:
        assert os.path.getsize(p) > 1000, p


def test_duplicate_ks_deduped(two_group_data):
    res = nmfconsensus(two_group_data, ks=(2, 2, 3, 2), restarts=3,
                       max_iter=100, use_mesh=False)
    assert res.ks == (2, 3)
    assert len(res.summary().splitlines()) == 4  # header + 2 ranks + best


def test_best_k_breaks_rho_ties_by_dispersion():
    """Exact rho ties (clean designs reach 1.0 at several ranks after
    signif-4 rounding) resolve toward the crisper consensus."""
    from nmfx.api import ConsensusResult, KResult

    def kres(k, rho, disp):
        n = 4
        return KResult(k=k, consensus=np.eye(n), rho=rho, dispersion=disp,
                       membership=np.ones(n, np.int64),
                       order=np.arange(n), iterations=np.ones(2, np.int32),
                       dnorms=np.ones(2), stop_reasons=np.ones(2, np.int32),
                       best_w=np.ones((5, k)), best_h=np.ones((k, n)))

    res = ConsensusResult(ks=(2, 3, 4),
                          per_k={2: kres(2, 1.0, 0.56),
                                 3: kres(3, 1.0, 1.0),
                                 4: kres(4, 0.99, 1.0)},
                          col_names=("a", "b", "c", "d"))
    assert res.best_k == 3  # rho tie 2-vs-3 -> higher dispersion wins
