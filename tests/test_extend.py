"""Incremental consensus (ISSUE 16): re-running the same (data,
config) at a WIDENED restarts/ks budget resumes the checkpoint ledger,
solves only the delta chunks, and is BIT-IDENTICAL to a from-scratch
run at the widened budget.

Why this can be exact: restart r's key is ``split(fold_in(key(seed),
k), R)[r]``, which counter-mode threefry makes independent of the
total budget R — so a chunk record solved under restarts=4 is the same
bits the restarts=8 run would solve for those rows, and the ledger's
manifest treats a budget change as an extension (``ck.extended``), not
a cold start. The engine matrix mirrors tests/test_checkpoint.py;
heavier families ride the slow tier."""

import numpy as np
import pytest

from test_checkpoint import assert_bit_identical

from nmfx import checkpoint as ckpt
from nmfx.api import nmfconsensus
from nmfx.config import CheckpointConfig, SolverConfig

KW = dict(ks=(2, 3), seed=5)


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=60, n_per_group=10, seed=7)


def _run(data, path, scfg, restarts, chunk=2, **over):
    kw = dict(KW, **over)
    cp = CheckpointConfig(directory=str(path), every_n_restarts=chunk)
    return nmfconsensus(data, solver_cfg=scfg, max_iter=None,
                        checkpoint=cp, restarts=restarts, **kw)


def _extended_count() -> int:
    return int(ckpt._extended_total.total())


#: tier-1 covers the three fast chunk-executor routes (the ISSUE 16
#: acceptance matrix); als/kl ride the slow tier
ENGINES = [
    pytest.param(SolverConfig(algorithm="mu", max_iter=30),
                 id="mu-packed"),
    pytest.param(SolverConfig(algorithm="mu", max_iter=30,
                              backend="vmap"), id="mu-vmap"),
    pytest.param(SolverConfig(algorithm="hals", max_iter=30),
                 id="hals"),
]

ENGINES_SLOW = [
    pytest.param(SolverConfig(algorithm="als", max_iter=30), id="als"),
    pytest.param(SolverConfig(algorithm="kl", max_iter=30), id="kl"),
]


def _restart_widening_roundtrip(small_data, tmp_path, scfg):
    """restarts 4 -> 8 over one ledger: only the delta chunks solve,
    the extension flag/counter fire, and the result is bit-identical to
    a fresh restarts=8 run."""
    _run(small_data, tmp_path / "inc", scfg, restarts=4)
    solved = ckpt.chunks_solved_count()
    ext0 = _extended_count()
    wide = _run(small_data, tmp_path / "inc", scfg, restarts=8)
    # chunk plan for 8 with chunk=2 is 4 chunks/rank; the first 2 per
    # rank are served from the restarts=4 records — only 2×|ks| solve
    assert ckpt.chunks_solved_count() == solved + 2 * len(KW["ks"])
    assert _extended_count() == ext0 + 1
    fresh = _run(small_data, tmp_path / "fresh", scfg, restarts=8)
    assert_bit_identical(wide, fresh)


@pytest.mark.parametrize("scfg", ENGINES)
def test_restart_widening_bit_identical(small_data, tmp_path, scfg):
    _restart_widening_roundtrip(small_data, tmp_path, scfg)


@pytest.mark.slow
@pytest.mark.parametrize("scfg", ENGINES_SLOW)
def test_restart_widening_bit_identical_slow_engines(small_data,
                                                     tmp_path, scfg):
    _restart_widening_roundtrip(small_data, tmp_path, scfg)


def test_ks_widening_solves_only_new_ranks(small_data, tmp_path):
    """ks (2,) -> (2, 3): rank 2 replays from records (bit-identical to
    its narrow-run self), only rank 3's chunks solve, and the widened
    result matches a fresh (2, 3) run bit-for-bit."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    narrow = _run(small_data, tmp_path / "inc", scfg, restarts=4,
                  ks=(2,))
    solved = ckpt.chunks_solved_count()
    ext0 = _extended_count()
    wide = _run(small_data, tmp_path / "inc", scfg, restarts=4,
                ks=(2, 3))
    assert ckpt.chunks_solved_count() == solved + 2  # rank 3 only
    assert _extended_count() == ext0 + 1
    assert np.asarray(narrow.per_k[2].consensus).tobytes() == \
        np.asarray(wide.per_k[2].consensus).tobytes()
    fresh = _run(small_data, tmp_path / "fresh", scfg, restarts=4,
                 ks=(2, 3))
    assert_bit_identical(wide, fresh)


def test_combined_restarts_and_ks_widening(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "inc", scfg, restarts=4, ks=(2,))
    wide = _run(small_data, tmp_path / "inc", scfg, restarts=8,
                ks=(2, 3))
    fresh = _run(small_data, tmp_path / "fresh", scfg, restarts=8,
                 ks=(2, 3))
    assert_bit_identical(wide, fresh)


def test_pure_replay_is_not_an_extension(small_data, tmp_path):
    """A fully-checkpointed identical re-run is a replay: zero solves
    and NO extension counted (the counter means 'reused AND produced
    new work')."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "c", scfg, restarts=4)
    solved = ckpt.chunks_solved_count()
    ext0 = _extended_count()
    _run(small_data, tmp_path / "c", scfg, restarts=4)
    assert ckpt.chunks_solved_count() == solved
    assert _extended_count() == ext0


def test_narrowing_replays_prefix_records(small_data, tmp_path):
    """restarts 8 -> 4 re-plans to the narrow budget's chunk set, whose
    records all exist: nothing solves, and the result is bit-identical
    to a direct restarts=4 run."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "inc", scfg, restarts=8)
    solved = ckpt.chunks_solved_count()
    narrow = _run(small_data, tmp_path / "inc", scfg, restarts=4)
    assert ckpt.chunks_solved_count() == solved
    direct = _run(small_data, tmp_path / "direct", scfg, restarts=4)
    assert_bit_identical(narrow, direct)
