"""Solver property tests (SURVEY.md §4 test pyramid: solver invariants)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import SolverConfig
from nmfx.init import random_init
from nmfx.solvers import SOLVERS, StopReason, solve
from nmfx.solvers.base import residual_norm

ALGOS = list(SOLVERS)


def _problem(low_rank_data, k=None, seed=0):
    a, true_k = low_rank_data
    k = k or true_k
    w0, h0 = random_init(jax.random.key(seed), a.shape[0], a.shape[1], k)
    return jnp.asarray(a, jnp.float32), w0, h0


@pytest.mark.parametrize("algo", ALGOS)
def test_nonnegativity_and_residual_decrease(low_rank_data, algo):
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm=algo, max_iter=60)
    res = solve(a, w0, h0, cfg)
    assert bool(jnp.all(res.w >= 0)), "W must be non-negative"
    assert bool(jnp.all(res.h >= 0)), "H must be non-negative"
    assert float(res.dnorm) < float(residual_norm(a, w0, h0))
    assert np.isfinite(float(res.dnorm))


@pytest.mark.parametrize("algo", ["mu", "als", "neals"])
def test_low_rank_recovery(low_rank_data, algo):
    # A is exactly rank 3; ALS-family and mu should drive the residual small
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm=algo, max_iter=500)
    res = solve(a, w0, h0, cfg)
    rel = float(res.dnorm) / float(jnp.sqrt(jnp.mean(a**2)))
    assert rel < 0.05, f"{algo}: relative residual {rel}"


def test_mu_monotone_loss(low_rank_data):
    # Lee-Seung guarantee: ||A - WH|| never increases across mu iterations
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm="mu", use_class_stop=False,
                       use_tol_checks=False, max_iter=1)
    norms = [float(residual_norm(a, w0, h0))]
    w, h = w0, h0
    for _ in range(30):
        res = solve(a, w, h, cfg)
        w, h = res.w, res.h
        norms.append(float(res.dnorm))
    assert all(b <= a_ + 1e-5 for a_, b in zip(norms, norms[1:])), norms


def test_mu_class_stability_stop(low_rank_data):
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm="mu", max_iter=10000, use_tol_checks=False)
    res = solve(a, w0, h0, cfg)
    assert int(res.iterations) < 10000
    assert int(res.stop_reason) == StopReason.CLASS_STABLE
    # stop rule: 200 stable checks, every 2nd iteration => at least ~400 iters
    assert int(res.iterations) >= 2 * cfg.stable_checks


def test_tolx_stop_fires(low_rank_data):
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm="neals", max_iter=5000, tol_x=1e-5)
    res = solve(a, w0, h0, cfg)
    assert int(res.iterations) < 5000
    assert int(res.stop_reason) in (StopReason.TOL_X, StopReason.TOL_FUN)


@pytest.mark.parametrize("algo", ["pg", "alspg"])
def test_pg_family_stops_on_projgrad(low_rank_data, algo):
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm=algo, max_iter=300, tol_pg=1e-3)
    res = solve(a, w0, h0, cfg)
    assert np.isfinite(float(res.dnorm))
    # on an exactly low-rank problem the projected gradient should vanish
    assert int(res.stop_reason) in (StopReason.PG_TOL, StopReason.MAX_ITER)
    assert float(res.dnorm) < float(residual_norm(a, w0, h0))


@pytest.mark.parametrize("algo", [
    pytest.param(a, marks=[pytest.mark.slow] if a in ("pg", "alspg")
                 else [])  # the line-search family costs ~10s per lane
    for a in ALGOS])
def test_vmap_over_restarts(low_rank_data, algo):
    a, _, _ = _problem(low_rank_data)
    m, n = a.shape
    k = 3
    keys = jax.random.split(jax.random.key(1), 4)
    w0s, h0s = jax.vmap(lambda kk: random_init(kk, m, n, k))(keys)
    cfg = SolverConfig(algorithm=algo, max_iter=30)
    batched = jax.vmap(lambda w0, h0: solve(a, w0, h0, cfg))(w0s, h0s)
    assert batched.w.shape == (4, m, k)
    assert batched.h.shape == (4, k, n)
    # different seeds must give different runs
    assert not np.allclose(np.asarray(batched.w[0]), np.asarray(batched.w[1]))
    # batched result matches the unbatched solve lane-for-lane. als/neals get
    # loose tolerance (batched vs single LU/QR kernels differ in low-order
    # bits, compounding over iterations); the elementwise/matmul family keeps
    # the tight band so cross-lane contamination can't hide
    tol = dict(rtol=5e-3, atol=1e-3) if algo in ("als", "neals", "snmf") else \
        dict(rtol=2e-4, atol=2e-5)
    single = solve(a, w0s[0], h0s[0], cfg)
    np.testing.assert_allclose(np.asarray(batched.w[0]),
                               np.asarray(single.w), **tol)


def test_f64_parity_mode(low_rank_data):
    # dtype="float64" is the parity-testing path vs the reference's f64 BLAS
    a, w0, h0 = _problem(low_rank_data)
    cfg = SolverConfig(algorithm="mu", max_iter=20, dtype="float64")
    try:
        res = solve(a, w0, h0, cfg)
    except Exception:
        pytest.skip("x64 not enabled in this environment")
    if res.w.dtype == jnp.float64:
        assert np.isfinite(float(res.dnorm))


@pytest.mark.slow
@pytest.mark.parametrize("algo,backend", [("kl", "auto"), ("mu", "vmap")])
def test_restart_chunking_matches_unchunked(low_rank_data, algo, backend):
    """restart_chunk bounds concurrent lanes without changing results:
    per-restart keys are prefix-stable under jax.random.split, so chunked
    and unchunked sweeps see identical initializations."""
    from nmfx.sweep import sweep_one_k

    a, _ = low_rank_data
    cfg_full = SolverConfig(algorithm=algo, max_iter=80, backend=backend)
    cfg_chunk = SolverConfig(algorithm=algo, max_iter=80, backend=backend,
                             restart_chunk=3)
    key = jax.random.key(11)
    ref = sweep_one_k(a, key, k=3, restarts=7, solver_cfg=cfg_full,
                      mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=7, solver_cfg=cfg_chunk,
                      mesh=None)
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.dnorms),
                               np.asarray(ref.dnorms), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got.best_w),
                               np.asarray(ref.best_w), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("algo", ["mu", "kl", "neals"])
def test_solvers_clean_under_debug_nans(low_rank_data, algo):
    """PARITY aux claim: the solvers run under jax_debug_nans without
    tripping it. Scope caveat: the flag only inspects dispatched outputs,
    so this asserts the solve's *results* (factors, dnorm) are NaN-free on
    zero-heavy inputs — transient loop intermediates are not observable."""
    a, w0, h0 = _problem(low_rank_data)
    w0 = w0.at[:, 0].set(0.0)  # a dead component stresses the guards
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        res = solve(a, w0, h0, SolverConfig(algorithm=algo, max_iter=30))
        assert np.isfinite(float(res.dnorm))
        assert np.isfinite(np.asarray(res.w)).all()
        assert np.isfinite(np.asarray(res.h)).all()
    finally:
        jax.config.update("jax_debug_nans", prev)


@pytest.mark.slow
@pytest.mark.parametrize("shape,k", [((7, 31), 2), ((31, 7), 3),
                                     ((129, 5), 4), ((3, 3), 2),
                                     ((64, 2), 2)])
def test_solver_shapes_fuzz(shape, k):
    """Odd/tall/wide/tiny shapes through every solver: finite outputs,
    correct shapes, non-negativity (shape-specialization bugs — padding,
    reshapes, tile assumptions — surface here)."""
    m, n = shape
    if k > n:
        pytest.skip("k > n is rejected by the pipeline")
    rng = np.random.default_rng(m * 100 + n)
    a = jnp.asarray(rng.uniform(0.1, 1.0, (m, n)), jnp.float32)
    w0, h0 = random_init(jax.random.key(0), m, n, k)
    for algo in ALGOS:
        res = solve(a, w0, h0, SolverConfig(algorithm=algo, max_iter=25))
        assert res.w.shape == (m, k) and res.h.shape == (k, n), algo
        assert np.isfinite(np.asarray(res.w)).all(), algo
        assert np.isfinite(np.asarray(res.h)).all(), algo
        assert bool(jnp.all(res.w >= 0) & jnp.all(res.h >= 0)), algo


def test_base_helpers_units():
    """Direct pins on the shared convergence helpers (reference
    calculateMaxchange / the class-label rule)."""
    from nmfx.solvers.base import class_labels, maxchange, solve_gram_reg

    m0 = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    m1 = jnp.asarray([[1.0, 2.5], [3.0, 4.0]])
    # max|Δ| / (sqrt(eps) + max|prev|) — non-destructive, exact value
    expect = 0.5 / (np.sqrt(np.finfo(np.float32).eps) + 4.0)
    np.testing.assert_allclose(float(maxchange(m1, m0)), expect, rtol=1e-6)

    h = jnp.asarray([[0.1, 0.9, 0.5], [0.8, 0.2, 0.5]])
    np.testing.assert_array_equal(np.asarray(class_labels(h)), [1, 0, 0])

    # jittered Cholesky solve: healthy system matches plain solve
    rng = np.random.default_rng(0)
    g = rng.uniform(0.5, 1.0, (3, 3))
    gram = jnp.asarray(g @ g.T + 3 * np.eye(3), jnp.float32)
    rhs = jnp.asarray(rng.uniform(size=(3, 4)), jnp.float32)
    np.testing.assert_allclose(np.asarray(solve_gram_reg(gram, rhs)),
                               np.linalg.solve(np.asarray(gram),
                                               np.asarray(rhs)),
                               rtol=1e-4)
