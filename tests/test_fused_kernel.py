"""Round-7 fused (join-the-updates) pallas kernel + hals block kernel.

Two contracts pinned here, both in interpret mode on CPU (the hardware
twin is bench.py's fused-vs-phased rung, which hard-fails on any
parity break):

1. FUSED ≡ PHASED, bit-exact. ``experimental.fused_updates='fused'``
   swaps the phased W/H half-update grid for the PL-NMF blocking that
   runs the W-half of iteration p−1 and the H-half of iteration p on
   the same VMEM-resident A tile (A read once per iteration instead of
   twice). The dot_generals are the same ops in the same tile order
   with the same f32 accumulators, so the results must be
   BYTE-identical — iterations, stop reasons, AND factors, at every
   check_block. Anything weaker would let a "perf mode" fork numerics.

2. The hals block kernel rides the same slot scheduler with the same
   operand/export signature, so cadence semantics (stop decisions,
   budget fence, auto-resolution) transfer; its numerics agree with
   the vmapped dense hals engine at the consensus/label level (the
   coordinate sweep re-associates accumulations across the packed
   layout, so bit-equality is not the contract — the hardware gate's
   restart-equivalent band is).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import (ConsensusConfig, ExperimentalConfig, InitConfig,
                         SolverConfig)
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.sched_mu import mu_sched
from nmfx.sweep import sweep

KS = (4, 3, 2)
R = 5


@pytest.fixture(scope="module")
def jobs():
    a = jnp.asarray(grouped_matrix(200, (10, 10, 10), effect=2.0, seed=0),
                    jnp.float32)
    k_max = max(KS)
    root = jax.random.key(123)
    w0l, h0l = [], []
    for k in KS:
        keys = jax.random.split(jax.random.fold_in(root, k), R)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
    return a, jnp.concatenate(w0l), jnp.concatenate(h0l)


def _cfg(mode, check_block=1, max_iter=600, **kw):
    return SolverConfig(
        max_iter=max_iter, backend="pallas", check_block=check_block,
        experimental=ExperimentalConfig(fused_updates=mode), **kw)


def _assert_bit_equal(ref, got):
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_array_equal(np.asarray(ref.w), np.asarray(got.w))
    np.testing.assert_array_equal(np.asarray(ref.h), np.asarray(got.h))


# --------------------------------------------------------------------------
# contract 1: fused ≡ phased, bit-exact
# --------------------------------------------------------------------------

@pytest.mark.parametrize("ncheck", [1, 4])
def test_fused_phased_bit_exact(jobs, ncheck):
    """The whole exactness contract in one assert set: at the same
    check_block, fused and phased agree on EVERY recorded field —
    iterations, stop reasons, factors — byte for byte."""
    a, w0, h0 = jobs
    phased = mu_sched(a, w0, h0, _cfg("phased", ncheck), slots=6)
    fused = mu_sched(a, w0, h0, _cfg("fused", ncheck), slots=6)
    _assert_bit_equal(phased, fused)


def test_auto_resolves_to_phased(jobs):
    """fused_updates='auto' (the default) stays on the phased kernel —
    the round-6 numerics remain the default byte-for-byte; 'fused' is
    an opt-in (the autotuner's, or an explicit override)."""
    a, w0, h0 = jobs
    auto = mu_sched(a, w0, h0, SolverConfig(max_iter=100,
                                            backend="pallas"), slots=6)
    phased = mu_sched(a, w0, h0, _cfg("phased", "auto", max_iter=100),
                      slots=6)
    _assert_bit_equal(auto, phased)


def test_fused_multi_check_drift_bound_unchanged(jobs):
    """check_block=4 fused vs check_block=1 phased: stop DECISIONS exact
    (the boundary exports replay the same checks), factors within the
    SAME post-stop drift class the phased multi-check launch is held to
    (test_check_block.py) — fusing the halves must not widen it."""
    a, w0, h0 = jobs
    ref = mu_sched(a, w0, h0, _cfg("phased", 1), slots=6)
    got = mu_sched(a, w0, h0, _cfg("fused", 4), slots=6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    w_ref, w_got = np.asarray(ref.w), np.asarray(got.w)
    denom = np.maximum(np.abs(w_ref), 1e-3)
    assert np.max(np.abs(w_ref - w_got) / denom) < 0.25
    l_ref = np.asarray(jnp.argmax(ref.h, axis=1))
    l_got = np.asarray(jnp.argmax(got.h, axis=1))
    assert (l_ref != l_got).mean(axis=1).max() <= 0.05


def test_fused_max_iter_fence(jobs):
    """The in-kernel budget fence is mode-independent: a cap crossing
    mid-launch freezes every lane at exactly max_iter with factors
    bit-identical to the phased N=1 schedule."""
    from nmfx.solvers.base import StopReason

    a, w0, h0 = jobs
    ref = mu_sched(a, w0, h0, _cfg("phased", 1, max_iter=20), slots=4)
    got = mu_sched(a, w0, h0, _cfg("fused", 4, max_iter=20), slots=4)
    assert np.all(np.asarray(got.iterations) == 20)
    assert np.all(np.asarray(got.stop_reason) == StopReason.MAX_ITER)
    np.testing.assert_array_equal(np.asarray(ref.w), np.asarray(got.w))
    np.testing.assert_array_equal(np.asarray(ref.h), np.asarray(got.h))


def test_fused_kernel_direct_bit_exact():
    """The kernel pair below the scheduler: fused_block_iterations with
    fused=True vs False on identical packed operands — every output
    (factors, TolX stats, boundary snapshots) byte-identical."""
    from nmfx.ops.pallas_mu import fused_block_iterations

    m, n, k, slots, bm = 192, 32, 3, 2, 64
    rk = slots * k
    key = jax.random.key(7)
    ka, kw, kh = jax.random.split(key, 3)
    a = jax.random.uniform(ka, (m, n), jnp.float32, 0.1, 1.0)
    wp = jax.random.uniform(kw, (m, rk), jnp.float32, 0.1, 1.0)
    hp = jax.random.uniform(kh, (rk, n), jnp.float32, 0.1, 1.0)
    fcol = jnp.zeros((1, rk), jnp.float32)
    common = dict(k=k, iters=2, block_m=bm, interpret=True)
    for extra in (dict(),
                  dict(check_block=4,
                       budget_cols=jnp.full((1, rk), 1e9, jnp.float32))):
        ref = fused_block_iterations(a, wp, hp, fcol, fused=False,
                                     **common, **extra)
        got = fused_block_iterations(a, wp, hp, fcol, fused=True,
                                     **common, **extra)
        assert len(ref) == len(got)
        for r, g in zip(ref, got):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_block_m_override_same_decisions(jobs):
    """experimental.block_m reshapes the row tiling only: stop
    iterations/reasons are invariant (per-lane reductions don't cross
    row blocks in a decision-changing way at these shapes) and labels
    stay inside the class-stability band. Not bit-exactness — the W
    gram accumulates across row blocks, so tile count reorders f32
    adds; the contract is that TUNING the tile never changes what the
    user is told converged."""
    a, w0, h0 = jobs
    ref = mu_sched(a, w0, h0, _cfg("fused", 4), slots=6)
    cfg = SolverConfig(
        max_iter=600, backend="pallas", check_block=4,
        experimental=ExperimentalConfig(fused_updates="fused",
                                        block_m=128))
    got = mu_sched(a, w0, h0, cfg, slots=6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    l_ref = np.asarray(jnp.argmax(ref.h, axis=1))
    l_got = np.asarray(jnp.argmax(got.h, axis=1))
    assert (l_ref != l_got).mean(axis=1).max() <= 0.05


def test_fused_guards(jobs):
    """The mode is fenced, not silently ignored, off its route."""
    a, w0, h0 = jobs
    with pytest.raises(ValueError, match="fused_updates"):
        mu_sched(a, w0, h0, SolverConfig(
            algorithm="hals", max_iter=600, backend="pallas",
            experimental=ExperimentalConfig(fused_updates="fused")),
            slots=6)
    with pytest.raises(ValueError, match="fused_updates"):
        # max_iter not a multiple of check_every: off the block route
        mu_sched(a, w0, h0, SolverConfig(
            max_iter=601, backend="pallas",
            experimental=ExperimentalConfig(fused_updates="fused")),
            slots=6)
    with pytest.raises(ValueError, match="block_m"):
        mu_sched(a, w0, h0, SolverConfig(
            max_iter=600, backend="auto",
            experimental=ExperimentalConfig(block_m=256)), slots=6)
    with pytest.raises(ValueError, match="fused_updates"):
        ExperimentalConfig(fused_updates="always")
    with pytest.raises(ValueError, match="block_m"):
        ExperimentalConfig(block_m=100)


# --------------------------------------------------------------------------
# contract 2: the hals block kernel on the slot scheduler
# --------------------------------------------------------------------------

def test_hals_pallas_agreement(jobs):
    """hals on the pallas slot scheduler vs the vmapped dense hals
    engine, full sweep: consensus within the hardware gate's
    restart-equivalent band (mean|dC|·R ≤ 0.6) and labels within the
    class-stability band — the packed coordinate sweep re-associates
    f32 accumulation, so agreement, not bit-equality, is the
    contract."""
    a, _, _ = jobs
    ks, r = (2, 3), 4
    out = {}
    for backend in ("packed", "pallas"):
        scfg = SolverConfig(algorithm="hals", max_iter=400,
                            backend=backend)
        out[backend] = sweep(a, ConsensusConfig(ks=ks, restarts=r,
                                                grid_exec="grid"),
                             scfg, InitConfig(), None)
    for k in ks:
        dc = np.abs(np.asarray(out["packed"][k].consensus)
                    - np.asarray(out["pallas"][k].consensus))
        assert dc.mean() * r <= 0.6, (k, dc.mean() * r)
        l_ref = np.asarray(out["packed"][k].labels)
        l_got = np.asarray(out["pallas"][k].labels)
        assert (l_ref != l_got).mean(axis=1).max() <= 0.1, k


def test_hals_check_block_needs_tolfun_off(jobs):
    """hals's TolFun residual cannot be replayed from the kernel's
    boundary exports: explicit check_block>1 on the pallas hals route
    with TolFun armed is a hard error; with use_tol_checks=False the
    multi-check launch is sound and its stop DECISIONS match the
    check-per-trip schedule exactly."""
    a, w0, h0 = jobs
    with pytest.raises(ValueError, match="use_tol_checks"):
        mu_sched(a, w0, h0, SolverConfig(
            algorithm="hals", max_iter=200, backend="pallas",
            check_block=4), slots=6)
    base = SolverConfig(algorithm="hals", max_iter=200,
                        backend="pallas", use_tol_checks=False)
    ref = mu_sched(a, w0, h0, dataclasses.replace(base, check_block=1),
                   slots=6)
    got = mu_sched(a, w0, h0, dataclasses.replace(base, check_block=4),
                   slots=6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))


def test_hals_auto_check_block_resolves_to_one(jobs):
    """With TolFun armed (the default), 'auto' on the pallas hals route
    resolves to check-per-trip — bit-identical to explicit 1 — instead
    of erroring or silently disarming the residual test."""
    a, w0, h0 = jobs
    auto = mu_sched(a, w0, h0, SolverConfig(
        algorithm="hals", max_iter=200, backend="pallas"), slots=6)
    one = mu_sched(a, w0, h0, SolverConfig(
        algorithm="hals", max_iter=200, backend="pallas",
        check_block=1), slots=6)
    _assert_bit_equal(auto, one)


@pytest.mark.slow
def test_fused_phased_bit_exact_heavy():
    """The exactness contract at a shape big enough to cross several
    row blocks and slot reloads (marked slow; CI runs the 200-row
    slice above)."""
    a = jnp.asarray(grouped_matrix(1024, (512, 512), effect=2.0, seed=1),
                    jnp.float32)
    ks, r = (6, 4), 8
    out = {}
    for mode in ("phased", "fused"):
        scfg = _cfg(mode, 4, max_iter=400)
        out[mode] = sweep(a, ConsensusConfig(ks=ks, restarts=r,
                                             grid_exec="grid"),
                          scfg, InitConfig(), None)
    for k in ks:
        np.testing.assert_array_equal(
            np.asarray(out["phased"][k].iterations),
            np.asarray(out["fused"][k].iterations))
        np.testing.assert_array_equal(
            np.asarray(out["phased"][k].consensus),
            np.asarray(out["fused"][k].consensus))
