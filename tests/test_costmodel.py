"""Performance observatory (ISSUE 13): analytic cost models vs XLA's
own cost analysis, per-dispatch roofline attribution, and the
bench-trajectory regression judge.

Budget discipline: the cross-check compiles Python-unrolled update
steps at the smallest viable shape (48×24, k=3 — two tiny compiles per
engine); the serving integration test reuses the smallest serve
config; the regress tests are pure-host JSON work.
"""

import json
import shutil

import numpy as np
import pytest

from nmfx.config import SolverConfig
from nmfx.obs import costmodel as cm

M, N, K = 48, 24, 3


# ---------------------------------------------------------------------
# model table / coverage
# ---------------------------------------------------------------------

def test_universe_matches_coverage_live():
    """The acceptance invariant NMFX009 enforces, pinned directly:
    reachable engines == modeled engines, exactly."""
    assert cm.engine_universe() == cm.covered_engines()


def test_exempt_algorithms_report_none():
    for algo in cm.COSTMODEL_EXEMPT:
        assert cm.iteration_flops(algo, "vmap", M, N, K) is None
        assert cm.iteration_bytes(algo, "vmap", M, N, K) is None


def test_models_positive_and_rank_monotonic():
    for algo, fam in sorted(cm.covered_engines()):
        cfg = SolverConfig(algorithm=algo,
                           backend="sketched" if fam == "sketched"
                           else "auto")
        f3 = cm.iteration_flops(algo, fam, M, N, 3, cfg)
        f5 = cm.iteration_flops(algo, fam, M, N, 5, cfg)
        b3 = cm.iteration_bytes(algo, fam, M, N, 3, cfg)
        assert f3 > 0 and b3 > 0, (algo, fam)
        assert f5 > f3, f"{algo}/{fam}: FLOPs must grow with rank"


def test_pallas_bytes_below_packed():
    """The locality story the attribution exists to surface: the
    VMEM-resident kernel family moves fewer HBM bytes per iteration
    than the XLA dense family at the same shape (factor round-trips
    amortized over the in-launch iterations), so its modeled
    arithmetic intensity is strictly higher."""
    cfg = SolverConfig(algorithm="mu", backend="pallas")
    assert (cm.iteration_bytes("mu", "pallas", 5000, 500, 10, cfg)
            < cm.iteration_bytes("mu", "packed", 5000, 500, 10, cfg))
    assert (cm.iteration_flops("mu", "pallas", 5000, 500, 10, cfg)
            == cm.iteration_flops("mu", "packed", 5000, 500, 10, cfg))


def test_hals_pallas_bytes_below_packed():
    """The hals block kernel rides the same slot scheduler and VMEM
    residency as the mu kernel, so its modeled per-iteration traffic
    must sit below the XLA packed family at the same shape while the
    FLOPs stay identical (the permutation conjugation is O(per-launch),
    subleading — not modeled per iteration)."""
    cfg = SolverConfig(algorithm="hals", backend="pallas")
    assert (cm.iteration_bytes("hals", "pallas", 5000, 500, 10, cfg)
            < cm.iteration_bytes("hals", "packed", 5000, 500, 10, cfg))
    assert (cm.iteration_flops("hals", "pallas", 5000, 500, 10, cfg)
            == cm.iteration_flops("hals", "packed", 5000, 500, 10, cfg))


def test_fused_mu_bytes_encode_single_a_read():
    """The round-7 claim the costmodel must price honestly: the fused
    join-the-updates kernel reads each A tile ONCE per iteration
    ((T+1)/T passes per launch) where the phased kernel reads it twice
    — so fused bytes are strictly below phased at the same config, by
    less than the full A term (the +1 prologue pass), with FLOPs
    unchanged (the arithmetic is identical, only the locality moves)."""
    from nmfx.config import ExperimentalConfig

    def cfg(mode):
        return SolverConfig(
            algorithm="mu", backend="pallas",
            experimental=ExperimentalConfig(fused_updates=mode))

    m, n, k = 5000, 500, 10
    phased = cm.iteration_bytes("mu", "pallas", m, n, k, cfg("phased"))
    fused = cm.iteration_bytes("mu", "pallas", m, n, k, cfg("fused"))
    assert fused < phased
    # the delta is A-traffic only: strictly less than one full A pass
    # per iteration, and more than nothing
    a_pass = m * n * 4
    assert phased - fused < a_pass
    assert phased - fused > a_pass / 2  # (2 - (T+1)/T) ≈ 1 for real T
    assert (cm.iteration_flops("mu", "pallas", m, n, k, cfg("fused"))
            == cm.iteration_flops("mu", "pallas", m, n, k,
                                  cfg("phased")))
    # 'auto' prices as phased — the default numerics ARE phased
    auto = cm.iteration_bytes("mu", "pallas", m, n, k, SolverConfig(
        algorithm="mu", backend="pallas"))
    assert auto == phased


def test_dispatch_cost_resolves_family_and_sums():
    scfg = SolverConfig(algorithm="mu", max_iter=50)
    cost = cm.dispatch_cost(scfg, M, N, {2: [10, 20], 3: [5]})
    assert cost["family"] == "packed"  # mu auto resolves packed
    expect = (cm.iteration_flops("mu", "packed", M, N, 2, scfg) * 30
              + cm.iteration_flops("mu", "packed", M, N, 3, scfg) * 5)
    assert cost["flops"] == pytest.approx(expect)
    assert cost["arithmetic_intensity"] == pytest.approx(
        cost["flops"] / cost["bytes"])


def test_dispatch_cost_none_for_exempt():
    scfg = SolverConfig(algorithm="pg", max_iter=50)
    assert cm.dispatch_cost(scfg, M, N, {2: [10]}) is None


# ---------------------------------------------------------------------
# the XLA cross-check: analytic vs compiled.cost_analysis(), per engine
# ---------------------------------------------------------------------

#: pinned tolerance bands — analytic/XLA ratio per engine at the
#: smallest shape, measured on this image's jax 0.4.37 CPU backend and
#: given ~±0.1 headroom. The models are leading-order (k² terms and
#: fusion decisions move the ratio at tiny shapes), so the bands are
#: per-engine rather than one global epsilon — but they are BANDS, so
#: an extra GEMM slipping into an update (flops +33% for mu) or a model
#: constant edited without re-calibration fails here instead of
#: silently drifting the bench MFU record. als' flop band sits above
#: 1.0 by construction: its SVD lowers to a LAPACK custom call whose
#: FLOPs cost_analysis cannot see, so the analytic model (which prices
#: the SVD) necessarily exceeds the XLA count.
_FLOP_BANDS = {
    ("mu", "vmap"): (0.80, 1.00), ("mu", "packed"): (0.80, 1.00),
    ("mu", "sketched"): (0.75, 1.00),
    ("hals", "vmap"): (0.75, 1.00), ("hals", "packed"): (0.75, 1.00),
    ("hals", "sketched"): (0.65, 0.95),
    ("kl", "vmap"): (0.85, 1.10), ("kl", "packed"): (0.85, 1.10),
    ("als", "vmap"): (1.05, 1.45), ("als", "packed"): (1.05, 1.45),
    ("neals", "vmap"): (0.90, 1.20), ("neals", "packed"): (0.70, 1.00),
    ("snmf", "vmap"): (0.90, 1.20), ("snmf", "packed"): (0.70, 1.00),
}

_BYTE_BANDS = {
    ("mu", "vmap"): (0.75, 1.05), ("mu", "packed"): (0.70, 1.00),
    ("mu", "sketched"): (0.70, 1.00),
    ("hals", "vmap"): (0.85, 1.20), ("hals", "packed"): (0.70, 1.05),
    ("hals", "sketched"): (0.50, 0.80),
    ("kl", "vmap"): (0.80, 1.10), ("kl", "packed"): (0.80, 1.10),
    ("als", "vmap"): (0.80, 1.15), ("als", "packed"): (0.80, 1.15),
    ("neals", "vmap"): (0.75, 1.05), ("neals", "packed"): (0.60, 0.90),
    ("snmf", "vmap"): (0.75, 1.05), ("snmf", "packed"): (0.60, 0.90),
}


@pytest.mark.parametrize("algo,fam", sorted(
    e for e in _FLOP_BANDS))
def test_analytic_model_vs_xla_cost_analysis(algo, fam):
    """The pinned-tolerance gate: the analytic per-iteration model must
    track what XLA actually compiled for the engine's update step
    (differenced between unroll depths so setup cost cancels) — the
    guarantee that the table can never silently drift from the emitted
    program (ISSUE 13 acceptance)."""
    cfg = SolverConfig(algorithm=algo,
                       backend="sketched" if fam == "sketched"
                       else "auto")
    xla = cm.xla_iteration_cost(algo, fam, M, N, K, cfg)
    if xla is None:
        pytest.skip("no cost analysis on this backend")
    flops = cm.iteration_flops(algo, fam, M, N, K, cfg)
    lo, hi = _FLOP_BANDS[(algo, fam)]
    ratio = flops / xla["flops"]
    assert lo <= ratio <= hi, \
        f"{algo}/{fam}: analytic/XLA flops ratio {ratio:.3f} " \
        f"outside pinned [{lo}, {hi}]"
    if xla["bytes"] is not None:
        blo, bhi = _BYTE_BANDS[(algo, fam)]
        bratio = cm.iteration_bytes(algo, fam, M, N, K, cfg) \
            / xla["bytes"]
        assert blo <= bratio <= bhi, \
            f"{algo}/{fam}: analytic/XLA bytes ratio {bratio:.3f} " \
            f"outside pinned [{blo}, {bhi}]"


def test_pallas_crosscheck_unavailable_on_cpu():
    """Mosaic does not compile on CPU: the pallas family reports None
    (its FLOPs model is mu's — the same update math — and is
    cross-checked through the packed family above)."""
    cfg = SolverConfig(algorithm="mu", backend="pallas")
    assert cm.xla_iteration_cost("mu", "pallas", M, N, K, cfg) is None


# ---------------------------------------------------------------------
# per-dispatch attribution
# ---------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _attrib_state_isolated():
    was = cm.attribution_enabled()
    yield
    cm.reset_perf()
    if was:
        cm.enable_attribution()
    else:
        cm.disable_attribution()


def test_attribute_dispatch_records_and_verdicts():
    cm.reset_perf()
    scfg = SolverConfig(algorithm="mu", max_iter=50)
    rec = cm.attribute_dispatch("test.kind", scfg, M, N,
                                {2: [10, 10], 3: [10]}, solve_s=0.25)
    assert rec is not None
    cost = cm.dispatch_cost(scfg, M, N, {2: [10, 10], 3: [10]})
    assert rec["model_flops"] == pytest.approx(cost["flops"])
    assert rec["achieved_flops_per_s"] == pytest.approx(
        cost["flops"] / 0.25)
    summary = cm.perf_summary()
    assert summary["kinds"]["test.kind"]["dispatches"] == 1
    assert "verdict" in summary["kinds"]["test.kind"]
    # the per-dispatch drill-down ring retains the record
    tail = cm.recent_attributions(limit=1)
    assert tail and tail[-1]["kind"] == "test.kind"
    assert tail[-1]["model_flops"] == pytest.approx(cost["flops"])
    # histograms landed on the registry under the kind label
    from nmfx.obs import metrics

    hist = metrics.registry().get("nmfx_perf_achieved_flops")
    assert hist.series()[("test.kind",)]["count"] >= 1
    ai = metrics.registry().get("nmfx_perf_arithmetic_intensity")
    assert ai.series()[("test.kind",)]["count"] >= 1


def test_attribution_verdict_sides_of_the_ridge():
    """With an explicit peak the verdict names the binding wall: mu at
    tiny k is bandwidth-bound (AI ≈ k/2 FLOP/B, far under any TPU
    ridge); against a fictional low-bandwidth device the same dispatch
    flips compute-bound."""
    cm.reset_perf()
    scfg = SolverConfig(algorithm="mu", max_iter=50)
    kind_args = dict(m=M, n=N, iters_by_k={3: [20]}, solve_s=0.1)
    real_kind = None
    try:
        import jax

        real_kind = str(jax.devices()[0].device_kind)
        cm.set_device_peak(real_kind, 197e12, 819e9)
        rec = cm.attribute_dispatch("ridge.low", scfg, **kind_args)
        assert rec["mfu"] is not None
        assert "bandwidth-bound" in rec["verdict"]
        # a tiny FLOP peak with abundant bandwidth drops the ridge
        # below mu's AI — the same dispatch flips compute-bound
        cm.set_device_peak(real_kind, 1e6, 1e15)
        rec = cm.attribute_dispatch("ridge.high", scfg, **kind_args)
        assert "compute-bound" in rec["verdict"]
    finally:
        if real_kind is not None:
            with cm._peaks_lock:
                cm.DEVICE_PEAKS.pop(real_kind, None)


def test_attribution_disabled_and_guards():
    cm.disable_attribution()
    scfg = SolverConfig(algorithm="mu")
    assert cm.attribute_dispatch("x", scfg, M, N, {2: [5]}, 0.1) is None
    cm.enable_attribution()
    # zero/None wall never divides
    assert cm.attribute_dispatch("x", scfg, M, N, {2: [5]}, 0.0) is None
    assert cm.attribute_dispatch("x", scfg, M, N, {2: [5]},
                                 None) is None
    # exempt algorithm: no model, no record
    assert cm.attribute_dispatch(
        "x", SolverConfig(algorithm="pg"), M, N, {2: [5]}, 0.1) is None
    assert cm.perf_summary()["kinds"] == {}


def test_profiled_sweep_attributes_and_reports():
    """End-to-end on the default profiled path: a real sweep annotates
    its dispatch, the perf table shows up in Profiler.report(), and
    the histograms export through prometheus text."""
    from nmfx.datasets import two_group_matrix
    from nmfx.obs import metrics
    from nmfx.profiling import Profiler
    from nmfx.sweep import sweep
    from nmfx.config import ConsensusConfig

    cm.reset_perf()
    a = two_group_matrix(n_genes=60, n_per_group=10, seed=3)
    prof = Profiler()
    with prof:
        sweep(a, ConsensusConfig(ks=(2,), restarts=2, seed=5),
              SolverConfig(max_iter=20), profiler=prof)
    kinds = cm.perf_summary()["kinds"]
    assert any(k.startswith("sweep.") for k in kinds), kinds
    report = prof.report()
    assert "perf attribution" in report
    text = metrics.registry().prometheus_text()
    assert "nmfx_perf_achieved_flops_bucket" in text


def test_served_request_exports_perf_metrics():
    """ISSUE 13 satellite: perf metrics appear in ``metrics_text()``
    (and the stats_snapshot perf summary) after a served request."""
    from nmfx.datasets import two_group_matrix
    from nmfx.exec_cache import ExecCache
    from nmfx.serve import NMFXServer, ServeConfig

    cm.reset_perf()
    a = two_group_matrix(n_genes=60, n_per_group=10, seed=3)
    with NMFXServer(ServeConfig(), exec_cache=ExecCache()) as srv:
        srv.submit(a, ks=(2,), restarts=2, seed=11,
                   solver_cfg=SolverConfig(max_iter=30)).result(
                       timeout=600)
        snap = srv.stats_snapshot()
        text = srv.metrics_text()
    assert "serve" in snap["perf"]["kinds"]
    assert snap["perf"]["kinds"]["serve"]["dispatches"] >= 1
    assert 'nmfx_perf_achieved_flops_count{kind="serve"}' in text
    assert 'nmfx_perf_arithmetic_intensity_count{kind="serve"}' in text


# ---------------------------------------------------------------------
# regression observatory (nmfx.obs.regress / nmfx-perf)
# ---------------------------------------------------------------------

def _repo_root():
    import nmfx

    import os

    return os.path.dirname(os.path.dirname(
        os.path.abspath(nmfx.__file__)))


def test_regress_loads_all_shipped_rounds_and_reports():
    from nmfx.obs import regress

    rounds = regress.load_rounds(_repo_root())
    assert [r["round"] for r in rounds] == [1, 2, 3, 4, 5]
    # schema drift normalized: r01 predates mfu, r05 has it
    assert "mfu" not in rounds[0]["metrics"]
    assert "mfu" in rounds[4]["metrics"]
    report = regress.markdown_report(rounds, regress.compare(rounds))
    assert "consensus_sweep_wall_s" in report
    assert "r01" in report and "r05" in report


def test_regress_path_selectors_and_wrapper_forms():
    from nmfx.obs import regress

    rec = {"parsed": {"metric": "consensus_sweep_wall_s", "value": 2.0,
                      "detail": {"serve": {"ladder": [
                          {"offered_load": 0.5, "p50_latency_s": 9.0},
                          {"offered_load": 1.0, "p50_latency_s": 3.0},
                      ]}}}}
    got = regress.extract_metrics(rec)
    assert got["consensus_sweep_wall_s"] == 2.0
    assert got["serve_p50_latency_s"] == 3.0
    # bare (unwrapped) records normalize identically
    assert regress.extract_metrics(rec["parsed"]) == got


def test_regress_verdict_red_on_degraded_r05_copy(tmp_path):
    """The acceptance scenario: a synthetically degraded copy of
    BENCH_r05 as the newest round flips the verdict red (exit 2
    through the nmfx-perf entrypoint), while a copy of the best round
    stays green."""
    import os

    from nmfx.obs import regress

    root = _repo_root()
    for name in os.listdir(root):
        if name.startswith("BENCH_r0") and name.endswith(".json"):
            shutil.copy(os.path.join(root, name), tmp_path / name)
    with open(tmp_path / "BENCH_r03.json") as f:
        best = json.load(f)

    # green control first: the best round re-measured as r06
    shutil.copy(tmp_path / "BENCH_r03.json",
                tmp_path / "BENCH_r06.json")
    assert regress.main(["--dir", str(tmp_path)]) == 0

    degraded = json.loads(json.dumps(best))
    degraded["parsed"]["value"] *= 2.0
    degraded["parsed"]["detail"]["restarts_per_s"] /= 2.0
    with open(tmp_path / "BENCH_r06.json", "w") as f:
        json.dump(degraded, f)
    assert regress.main(["--dir", str(tmp_path),
                         "--json", str(tmp_path / "verdict.json"),
                         "--markdown",
                         str(tmp_path / "trend.md")]) == 2
    with open(tmp_path / "verdict.json") as f:
        verdict = json.load(f)
    assert verdict["status"] == "regression"
    regressed = {r["metric"] for r in verdict["regressions"]}
    assert "consensus_sweep_wall_s" in regressed
    assert "restarts_per_s" in regressed
    # every regression names the round that set the bar
    assert all(r["best_round"] for r in verdict["regressions"])
    trend = (tmp_path / "trend.md").read_text()
    assert "Regressions" in trend


def test_regress_candidate_mode_and_missing_metric(tmp_path):
    """--candidate judges an out-of-tree record against all loaded
    rounds; a metric priors had but the candidate lacks is reported
    as missing, not silently dropped."""
    from nmfx.obs import regress

    rounds = regress.load_rounds(_repo_root())
    cand = {"file": "x", "metrics": {"consensus_sweep_wall_s": 1.30}}
    verdict = regress.compare(rounds, cand)
    assert verdict["status"] == "ok"
    assert any(m["metric"] == "restarts_per_s"
               for m in verdict["missing"])
    improved = {m["metric"] for m in verdict["improvements"]}
    assert "consensus_sweep_wall_s" in improved  # beats r03's 1.384


def test_regress_zero_bar_stays_judgeable():
    """A best-prior bar of exactly 0 (a rounded-to-zero overhead
    fraction) must not make the metric permanently unjudgeable: a
    clearly-worse candidate still regresses, an equal one stays ok."""
    from nmfx.obs import regress

    rounds = [{"round": 1, "file": "BENCH_r01.json",
               "metrics": {"obs_overhead_frac": 0.0}}]
    bad = regress.compare(rounds, {"file": "x", "metrics":
                                   {"obs_overhead_frac": 0.5}})
    assert any(r["metric"] == "obs_overhead_frac"
               for r in bad["regressions"])
    same = regress.compare(rounds, {"file": "x", "metrics":
                                    {"obs_overhead_frac": 0.0}})
    assert same["status"] == "ok"


def test_attribution_aggregate_mfu_uses_device_seconds():
    """perf_summary's MFU divides by DEVICE-seconds: the same dispatch
    attributed over 4 devices reports a quarter of the single-device
    aggregate MFU (matching the per-record math)."""
    import jax

    kind = str(jax.devices()[0].device_kind)
    cm.reset_perf()
    scfg = SolverConfig(algorithm="mu", max_iter=50)
    try:
        cm.set_device_peak(kind, 1e12, 1e12)
        cm.attribute_dispatch("one.dev", scfg, M, N, {3: [20]}, 0.1,
                              devices=1)
        cm.attribute_dispatch("four.dev", scfg, M, N, {3: [20]}, 0.1,
                              devices=4)
        kinds = cm.perf_summary()["kinds"]
        assert kinds["one.dev"]["mfu"] == pytest.approx(
            4 * kinds["four.dev"]["mfu"])
        recs = {r["kind"]: r for r in cm.recent_attributions()}
        assert kinds["four.dev"]["mfu"] == pytest.approx(
            recs["four.dev"]["mfu"])
    finally:
        with cm._peaks_lock:
            cm.DEVICE_PEAKS.pop(kind, None)


def test_regress_no_rounds(tmp_path):
    from nmfx.obs import regress

    assert regress.load_rounds(str(tmp_path)) == []
    assert regress.main(["--dir", str(tmp_path)]) == 1


def test_regress_corrupt_round_skipped(tmp_path):
    from nmfx.obs import regress

    (tmp_path / "BENCH_r01.json").write_text("{not json")
    shutil.copy(_repo_root() + "/BENCH_r05.json",
                tmp_path / "BENCH_r05.json")
    rounds = regress.load_rounds(str(tmp_path))
    assert [r["round"] for r in rounds] == [5]


# ---------------------------------------------------------------------
# communication model (ISSUE 19): analytic collective schedule vs the
# compiled HLO's allreduce ops — the FLOPs-vs-cost_analysis discipline
# applied to bytes-over-interconnect
# ---------------------------------------------------------------------

def test_comm_covered_matches_grid_driver():
    """Coverage invariant: exactly the engines the grid-sharded driver
    accepts (GRID_SOLVERS plus packed mu) have a comm model."""
    from nmfx.sweep import GRID_SOLVERS

    assert cm.comm_covered_algorithms() == frozenset(GRID_SOLVERS) | {"mu"}
    with pytest.raises(ValueError, match="no communication model"):
        cm.comm_model("pg", M, N, K)


def test_comm_model_restart_only_is_communication_avoiding():
    """The mesh tier's central claim: a restart-only mesh moves ZERO
    bytes per iteration — every lane is independent; only the per-k
    consensus epilogue reduces over the restart axis."""
    for alg in sorted(cm.comm_covered_algorithms()):
        model = cm.comm_model(alg, M, N, K, restart_shards=4, restarts=8)
        assert model["collectives_per_iter"] == 0, alg
        assert model["payload_bytes_per_iter"] == 0.0, alg
        assert model["wire_bytes_per_iter"] == 0.0, alg
        assert model["epilogue"]["payload_bytes"] > 0, alg


def test_comm_model_validation_and_scaling():
    with pytest.raises(ValueError, match=">= 1"):
        cm.comm_model("kl", M, N, K, feature_shards=0)
    one = cm.comm_model("kl", M, N, K, feature_shards=2, restarts=1)
    two = cm.comm_model("kl", M, N, K, feature_shards=2, restarts=2)
    # payloads scale with the local lane count (factors carry r_loc)
    assert two["payload_bytes_per_iter"] == 2 * one["payload_bytes_per_iter"]
    # wire bytes follow the ring convention: 2(p-1)/p of payload
    per = one["per_axis"]["features"]
    assert per["participants"] == 2
    assert per["wire_bytes"] == pytest.approx(per["payload_bytes"])


@pytest.mark.parametrize("alg,ops", [("kl", 4), ("mu", 6)])
def test_comm_model_matches_compiled_hlo(alg, ops):
    """Exact-count, exact-payload cross-validation on a 1×2×2 grid mesh
    (2 allreduces per grid axis per iteration for the generic drivers,
    3 for packed mu). The heavier engines ride the bench's detail.mesh
    comm gate; here the two serving defaults pin the contract in
    tier-1."""
    from nmfx.sweep import grid_mesh

    mesh = grid_mesh(1, 2, 2)
    model = cm.comm_model(alg, M, N, K, feature_shards=2,
                          sample_shards=2, restarts=2)
    meas = cm.xla_comm_cost(alg, M, N, K, mesh, r_loc=2)
    assert meas is not None, "HLO collective measurement unavailable"
    assert model["collectives_per_iter"] == ops
    assert meas["collectives_per_iter"] == model["collectives_per_iter"]
    assert meas["payload_bytes_per_iter"] == pytest.approx(
        model["payload_bytes_per_iter"], rel=0.01)
