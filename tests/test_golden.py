"""Golden-value tests: NumPy (f64) transliterations of the reference's
update math driven lockstep against the framework's solvers (SURVEY.md §4's
cross-implementation oracle, replacing the reference's dormant comparison
against the original BROAD script, test_nmf.r:29)."""

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import SolverConfig
from nmfx.solvers.base import residual_norm, solve


def _mu_numpy(a, w, h, iters, eps=1e-9):
    """Reference mu update (libnmf/nmf_mu.c:174-216): H then W with the new
    H, exact-zero short-circuit, in f64."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        numerh = w.T @ a
        h_new = h * numerh / ((w.T @ w) @ h + eps)
        h_new[(h == 0) | (numerh == 0)] = 0.0
        h = h_new
        numerw = a @ h.T
        w_new = w * numerw / (w @ (h @ h.T) + eps)
        w_new[(w == 0) | (numerw == 0)] = 0.0
        w = w_new
    return w, h


def _als_numpy(a, w, h, iters):
    """Reference ALS half-steps (libnmf/nmf_als.c:216-298): least squares
    then clamp negatives to zero, H first, W with the new H."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        h = np.maximum(np.linalg.lstsq(w, a, rcond=None)[0], 0.0)
        w = np.maximum(np.linalg.lstsq(h.T, a.T, rcond=None)[0].T, 0.0)
    return w, h


def _problem(seed=12, m=60, n=22, k=3):
    rng = np.random.default_rng(seed)
    a = rng.uniform(0.1, 1.0, (m, n))
    w0 = rng.uniform(0.1, 1.0, (m, k))
    h0 = rng.uniform(0.1, 1.0, (k, n))
    return a, w0, h0


def _run(algo, a, w0, h0, iters):
    cfg = SolverConfig(algorithm=algo, max_iter=iters, use_class_stop=False,
                       use_tol_checks=False)
    return solve(jnp.asarray(a, jnp.float32), jnp.asarray(w0, jnp.float32),
                 jnp.asarray(h0, jnp.float32), cfg)


def test_mu_matches_numpy_reference_math():
    a, w0, h0 = _problem()
    w_ref, h_ref = _mu_numpy(a, w0, h0, iters=25)
    res = _run("mu", a, w0, h0, iters=25)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=2e-3,
                               atol=1e-4)


def test_als_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=5)
    w_ref, h_ref = _als_numpy(a, w0, h0, iters=10)
    res = _run("als", a, w0, h0, iters=10)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def test_als_rank_deficient_stays_finite():
    """Duplicate W columns: the reference leans on dgeqp3 pivoting here; our
    min-norm least squares must stay finite and reduce the residual."""
    rng = np.random.default_rng(2)
    m, n, k = 40, 15, 3
    a = jnp.asarray(rng.uniform(0.5, 1.5, (m, k)) @
                    rng.uniform(0.5, 1.5, (k, n)), jnp.float32)
    col = rng.uniform(0.1, 1.0, (m, 1))
    w0 = jnp.asarray(np.concatenate([col] * k, axis=1), jnp.float32)
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, (k, n)), jnp.float32)
    res = solve(a, w0, h0, SolverConfig(algorithm="als", max_iter=40))
    assert np.isfinite(np.asarray(res.w)).all()
    assert np.isfinite(np.asarray(res.h)).all()
    assert float(res.dnorm) < float(residual_norm(a, w0, h0))


def _kl_numpy(a, w, h, iters, eps=1e-9):
    """Brunet (2004) divergence updates in f64 — the BROAD nmfconsensus.R
    model family the reference replaced with Euclidean mu (see
    nmfx/solvers/kl.py); H first, W with the fresh H."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        h = h * (w.T @ (a / (w @ h + eps))) / (w.sum(axis=0)[:, None] + eps)
        w = w * ((a / (w @ h + eps)) @ h.T) / (h.sum(axis=1)[None, :] + eps)
    return w, h


def test_kl_matches_numpy_brunet_math():
    a, w0, h0 = _problem(seed=9)
    w_ref, h_ref = _kl_numpy(a, w0, h0, iters=25)
    res = _run("kl", a, w0, h0, iters=25)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=2e-3,
                               atol=1e-4)


def test_kl_monotone_divergence():
    """Brunet guarantee: D(A || WH) never increases across iterations."""
    from nmfx.solvers.kl import kl_divergence

    a, w0, h0 = _problem(seed=4)
    a, w, h = (jnp.asarray(x, jnp.float32) for x in (a, w0, h0))
    cfg = SolverConfig(algorithm="kl", use_class_stop=False,
                       use_tol_checks=False, max_iter=1)
    divs = [float(kl_divergence(a, w, h))]
    for _ in range(30):
        res = solve(a, w, h, cfg)
        w, h = res.w, res.h
        divs.append(float(kl_divergence(a, w, h)))
    assert all(b <= d + 1e-4 * abs(d) for d, b in zip(divs, divs[1:])), divs
