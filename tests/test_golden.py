"""Golden-value tests: NumPy (f64) transliterations of the reference's
update math driven lockstep against the framework's solvers (SURVEY.md §4's
cross-implementation oracle, replacing the reference's dormant comparison
against the original BROAD script, test_nmf.r:29)."""

import jax.numpy as jnp
import numpy as np

from nmfx.config import SolverConfig
from nmfx.solvers.base import residual_norm, solve


def _mu_numpy(a, w, h, iters, eps=1e-9):
    """Reference mu update (libnmf/nmf_mu.c:174-216): H then W with the new
    H, exact-zero short-circuit, in f64."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        numerh = w.T @ a
        h_new = h * numerh / ((w.T @ w) @ h + eps)
        h_new[(h == 0) | (numerh == 0)] = 0.0
        h = h_new
        numerw = a @ h.T
        w_new = w * numerw / (w @ (h @ h.T) + eps)
        w_new[(w == 0) | (numerw == 0)] = 0.0
        w = w_new
    return w, h


def _als_numpy(a, w, h, iters):
    """Reference ALS half-steps (libnmf/nmf_als.c:216-298): least squares
    then clamp negatives to zero, H first, W with the new H."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        h = np.maximum(np.linalg.lstsq(w, a, rcond=None)[0], 0.0)
        w = np.maximum(np.linalg.lstsq(h.T, a.T, rcond=None)[0].T, 0.0)
    return w, h


def _problem(seed=12, m=60, n=22, k=3):
    rng = np.random.default_rng(seed)
    a = rng.uniform(0.1, 1.0, (m, n))
    w0 = rng.uniform(0.1, 1.0, (m, k))
    h0 = rng.uniform(0.1, 1.0, (k, n))
    return a, w0, h0


def _run(algo, a, w0, h0, iters):
    cfg = SolverConfig(algorithm=algo, max_iter=iters, use_class_stop=False,
                       use_tol_checks=False)
    return solve(jnp.asarray(a, jnp.float32), jnp.asarray(w0, jnp.float32),
                 jnp.asarray(h0, jnp.float32), cfg)


def test_mu_matches_numpy_reference_math():
    a, w0, h0 = _problem()
    w_ref, h_ref = _mu_numpy(a, w0, h0, iters=25)
    res = _run("mu", a, w0, h0, iters=25)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=2e-3,
                               atol=1e-4)


def test_als_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=5)
    w_ref, h_ref = _als_numpy(a, w0, h0, iters=10)
    res = _run("als", a, w0, h0, iters=10)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def test_als_rank_deficient_stays_finite():
    """Duplicate W columns: the reference leans on dgeqp3 pivoting here; our
    min-norm least squares must stay finite and reduce the residual."""
    rng = np.random.default_rng(2)
    m, n, k = 40, 15, 3
    a = jnp.asarray(rng.uniform(0.5, 1.5, (m, k)) @
                    rng.uniform(0.5, 1.5, (k, n)), jnp.float32)
    col = rng.uniform(0.1, 1.0, (m, 1))
    w0 = jnp.asarray(np.concatenate([col] * k, axis=1), jnp.float32)
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, (k, n)), jnp.float32)
    res = solve(a, w0, h0, SolverConfig(algorithm="als", max_iter=40))
    assert np.isfinite(np.asarray(res.w)).all()
    assert np.isfinite(np.asarray(res.h)).all()
    assert float(res.dnorm) < float(residual_norm(a, w0, h0))


def _solve_gram_reg_numpy(gram, rhs):
    """f64 mirror of base.solve_gram_reg: trace-scaled Tikhonov jitter +
    Cholesky solve (the shape-stable replacement for the reference's lazy
    QR fallback, nmf_neals.c:206-291)."""
    import scipy.linalg as sl

    k = gram.shape[0]
    lam = 10 * np.finfo(gram.dtype).eps * (np.trace(gram) / k)
    gram = gram + (lam + np.finfo(gram.dtype).tiny) * np.eye(k)
    return sl.cho_solve(sl.cho_factor(gram), rhs)


def _neals_numpy(a, w, h, iters):
    """Reference normal-equation ALS (libnmf/nmf_neals.c:200-306) with the
    framework's jittered-Cholesky Gram solve, H then W with the new H."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        h = np.maximum(_solve_gram_reg_numpy(w.T @ w, w.T @ a), 0.0)
        w = np.maximum(_solve_gram_reg_numpy(h @ h.T, h @ a.T).T, 0.0)
    return w, h


def test_neals_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=17)
    w_ref, h_ref = _neals_numpy(a, w0, h0, iters=8)
    res = _run("neals", a, w0, h0, iters=8)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def _nndsvd_numpy(a, k):
    """f64 transliteration of nmfx.init.nndsvd_init (Boutsidis NNDSVD;
    reference generatematrix.c:145-247). Sign-invariant to the SVD's
    per-vector sign ambiguity (abs on the leading pair; the ± split swaps
    sides with the sign, and the dominant side is picked by norm product)."""
    a = np.asarray(a, np.float64)
    u, s, vt = np.linalg.svd(a, full_matrices=False)
    u, s, vt = u[:, :k], s[:k], vt[:k]
    w0 = np.sqrt(s[0]) * np.abs(u[:, :1])
    h0 = np.sqrt(s[0]) * np.abs(vt[:1, :])
    if k > 1:
        uj, vj = u[:, 1:], vt[1:, :].T
        up, un = np.maximum(uj, 0), np.maximum(-uj, 0)
        vp, vn = np.maximum(vj, 0), np.maximum(-vj, 0)
        nup, nun = np.linalg.norm(up, axis=0), np.linalg.norm(un, axis=0)
        nvp, nvn = np.linalg.norm(vp, axis=0), np.linalg.norm(vn, axis=0)
        termp, termn = nup * nvp, nun * nvn
        use_p = termp >= termn
        term = np.where(use_p, termp, termn)
        scale = np.sqrt(s[1:] * term)
        tiny = np.finfo(np.float64).tiny
        wcols = scale * np.where(use_p, up / np.maximum(nup, tiny),
                                 un / np.maximum(nun, tiny))
        hrows = scale * np.where(use_p, vp / np.maximum(nvp, tiny),
                                 vn / np.maximum(nvn, tiny))
        w0 = np.concatenate([w0, wcols], axis=1)
        h0 = np.concatenate([h0, hrows.T], axis=0)
    w0[w0 <= 0.0] = 0.0
    h0[h0 <= 0.0] = 0.0
    return w0, h0


def test_nndsvd_matches_numpy_reference_math():
    from nmfx.init import nndsvd_init

    a, _, _ = _problem(seed=41)
    w_ref, h_ref = _nndsvd_numpy(a, 3)
    w0, h0 = nndsvd_init(jnp.asarray(a, jnp.float32), 3)
    np.testing.assert_allclose(np.asarray(w0), w_ref, rtol=5e-3, atol=5e-5)
    np.testing.assert_allclose(np.asarray(h0), h_ref, rtol=5e-3, atol=5e-5)


def _hals_numpy(a, w, h, iters, eps=1e-9):
    """f64 transliteration of HALS (nmfx/solvers/hals.py; Cichocki & Phan
    2009): coordinate-wise exact minimizations against fresh values, H
    pass then W pass with the new H. Copies its factor inputs — unlike the
    other oracles it updates rows/columns IN PLACE, and np.asarray aliases
    f64 inputs (mutating the caller's w0/h0 would corrupt the
    comparison)."""
    a = np.asarray(a, np.float64)
    w = np.array(w, np.float64, copy=True)
    h = np.array(h, np.float64, copy=True)
    k = w.shape[1]
    for _ in range(iters):
        wta, wtw = w.T @ a, w.T @ w
        for j in range(k):
            h[j] = np.maximum(
                h[j] + (wta[j] - wtw[j] @ h) / (wtw[j, j] + eps), 0.0)
        aht, hht = a @ h.T, h @ h.T
        for j in range(k):
            w[:, j] = np.maximum(
                w[:, j] + (aht[:, j] - w @ hht[:, j]) / (hht[j, j] + eps),
                0.0)
    return w, h


def test_hals_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=23)
    w_ref, h_ref = _hals_numpy(a, w0, h0, iters=10)
    res = _run("hals", a, w0, h0, iters=10)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def test_hals_monotone_loss():
    """HALS' coordinate-wise exact minimizations never increase the
    Frobenius objective."""
    a, w0, h0 = _problem(seed=29)
    prev = np.inf
    for it in (2, 4, 6, 10, 16):
        res = _run("hals", a, w0, h0, iters=it)
        d = float(residual_norm(jnp.asarray(a, jnp.float32),
                                res.w, res.h))
        assert d <= prev + 1e-6, (it, d, prev)
        prev = d


def _kl_numpy(a, w, h, iters, eps=1e-9):
    """Brunet (2004) divergence updates in f64 — the BROAD nmfconsensus.R
    model family the reference replaced with Euclidean mu (see
    nmfx/solvers/kl.py); H first, W with the fresh H."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    for _ in range(iters):
        h = h * (w.T @ (a / (w @ h + eps))) / (w.sum(axis=0)[:, None] + eps)
        w = w * ((a / (w @ h + eps)) @ h.T) / (h.sum(axis=1)[None, :] + eps)
    return w, h


def test_kl_matches_numpy_brunet_math():
    a, w0, h0 = _problem(seed=9)
    w_ref, h_ref = _kl_numpy(a, w0, h0, iters=25)
    res = _run("kl", a, w0, h0, iters=25)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=2e-3,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=2e-3,
                               atol=1e-4)


def test_kl_monotone_divergence():
    """Brunet guarantee: D(A || WH) never increases across iterations."""
    from nmfx.solvers.kl import kl_divergence

    a, w0, h0 = _problem(seed=4)
    a, w, h = (jnp.asarray(x, jnp.float32) for x in (a, w0, h0))
    cfg = SolverConfig(algorithm="kl", use_class_stop=False,
                       use_tol_checks=False, max_iter=1)
    divs = [float(kl_divergence(a, w, h))]
    for _ in range(30):
        res = solve(a, w, h, cfg)
        w, h = res.w, res.h
        divs.append(float(kl_divergence(a, w, h)))
    assert all(b <= d + 1e-4 * abs(d) for d, b in zip(divs, divs[1:])), divs


# --- projected-gradient family (Lin 2007) ----------------------------------

def _pg_subprob_np(gram, ctc, x, tol, sub_max_iter=1000, sigma=0.01,
                   beta=0.1, max_ls=20):
    """f64 transliteration of the shared NNLS subsolver
    (nmfx/solvers/pg_common.py; reference pg_subprob_{h,w}.c) including the
    persistent step size, first-trial direction choice, and grow-mode
    equality bailout."""
    alpha = 1.0
    it = 0
    while it < sub_max_iter:
        it += 1
        grad = gram @ x - ctc
        mask = (grad < 0) | (x > 0)
        if np.sqrt(np.sum(np.where(mask, grad * grad, 0.0))) < tol:
            break
        xres, xp, decrease = x, x, None
        for t in range(1, max_ls + 1):
            xn = np.maximum(x - alpha * grad, 0.0)
            d = xn - x
            suff = ((1 - sigma) * np.vdot(grad, d)
                    + 0.5 * np.vdot(gram @ d, d)) < 0
            if t == 1:
                decrease = not suff
                xp = x
            eq = np.array_equal(xp, xn)
            if decrease and suff:
                xres = xn
                break
            if (not decrease) and ((not suff) or eq):
                xres = xp
                break
            if decrease:
                alpha *= beta
            else:
                alpha /= beta
                xp = xn
        x = xres
    return x, gram @ x - ctc, it


def _alspg_numpy(a, w, h, iters, tol_pg=0.0):
    """f64 transliteration of alspg (nmfx/solvers/alspg.py; reference
    nmf_alspg.c): W-then-H subproblems with x0.1 tolerance tightening on
    1-iteration returns."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    gradw = w @ (h @ h.T) - a @ h.T
    gradh = (w.T @ w) @ h - w.T @ a
    initgrad = np.sqrt(np.sum(gradw**2) + np.sum(gradh**2))
    tolw = tolh = max(tol_pg, 0.001) * initgrad
    for _ in range(iters):
        x, gw, itw = _pg_subprob_np(h @ h.T, h @ a.T, w.T, tolw)
        w = x.T
        if itw == 1:
            tolw *= 0.1
        x, gradh, ith = _pg_subprob_np(w.T @ w, w.T @ a, h, tolh)
        h = x
        if ith == 1:
            tolh *= 0.1
        gradw = gw.T
    return w, h


def _pg_numpy(a, w, h, iters, sigma=0.01, beta=0.1, max_trials=40):
    """f64 transliteration of the direct pg solver (nmfx/solvers/pg.py;
    reference nmf_pg.c): first-iteration H polish + objective seed, then
    joint adaptive-step projected line searches."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    h, _, _ = _pg_subprob_np(w.T @ w, w.T @ a, h, 0.001)
    obj = 0.5 * np.sum((a - w @ h) ** 2)
    alpha = 1.0
    for _ in range(2, iters + 1):
        gradw = w @ (h @ h.T) - a @ h.T
        gradh = (w.T @ w) @ h - w.T @ a

        def trial(al):
            wn = np.maximum(w - al * gradw, 0.0)
            hn = np.maximum(h - al * gradh, 0.0)
            newobj = 0.5 * np.sum((a - wn @ hn) ** 2)
            compval = np.vdot(gradw, wn - w) + np.vdot(gradh, hn - h)
            return wn, hn, newobj, (newobj - obj) > sigma * compval

        wp, hp, objp, fail0 = trial(alpha)
        decrease = fail0
        wres, hres, objres = w, h, obj
        for _t in range(1, max_trials + 1):
            alpha = alpha * beta if decrease else alpha / beta
            wn, hn, newobj, fail = trial(alpha)
            eq = np.array_equal(wn, wp) and np.array_equal(hn, hp)
            if decrease and not fail:
                wres, hres, objres = wn, hn, newobj
                break
            if (not decrease) and (fail or eq):
                wres, hres, objres = wp, hp, objp
                alpha *= beta  # back off to the accepted candidate's step
                break
            if not decrease:
                wp, hp, objp = wn, hn, newobj
        w, h, obj = wres, hres, objres
    return w, h


def _run_pg(algo, a, w0, h0, iters):
    cfg = SolverConfig(algorithm=algo, max_iter=iters, tol_pg=0.0,
                       use_class_stop=False, use_tol_checks=False)
    return solve(jnp.asarray(a, jnp.float32), jnp.asarray(w0, jnp.float32),
                 jnp.asarray(h0, jnp.float32), cfg)


def test_alspg_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=21)
    w_ref, h_ref = _alspg_numpy(a, w0, h0, iters=5)
    res = _run_pg("alspg", a, w0, h0, iters=5)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def test_pg_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=31)
    w_ref, h_ref = _pg_numpy(a, w0, h0, iters=6)
    res = _run_pg("pg", a, w0, h0, iters=6)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


# --- sparse NMF (Kim & Park 2007) ------------------------------------------

def _snmf_numpy(a, w, h, iters, beta, eta):
    """f64 transliteration of SNMF/R (nmfx/solvers/snmf.py): regularized
    normal-equation half-steps with clamp, through the same
    jittered-Cholesky Gram solve as the solver (rtol-1e-10 lockstep needs
    the jitter too — it is ~1e-14-relative but not zero)."""
    a, w, h = (np.asarray(x, np.float64) for x in (a, w, h))
    k = w.shape[1]
    for _ in range(iters):
        h = np.maximum(_solve_gram_reg_numpy(w.T @ w + beta * np.ones((k, k)),
                                             w.T @ a), 0.0)
        w = np.maximum(_solve_gram_reg_numpy(h @ h.T + eta * np.eye(k),
                                             h @ a.T).T, 0.0)
    return w, h


def test_snmf_matches_numpy_reference_math():
    a, w0, h0 = _problem(seed=17)
    beta, eta = 0.05, float(np.max(a)) ** 2
    w_ref, h_ref = _snmf_numpy(a, w0, h0, iters=15, beta=beta, eta=eta)
    cfg = SolverConfig(algorithm="snmf", max_iter=15, sparsity_beta=beta,
                       use_class_stop=False, use_tol_checks=False)
    res = solve(jnp.asarray(a, jnp.float32), jnp.asarray(w0, jnp.float32),
                jnp.asarray(h0, jnp.float32), cfg)
    np.testing.assert_allclose(np.asarray(res.w), w_ref, rtol=5e-3,
                               atol=5e-4)
    np.testing.assert_allclose(np.asarray(res.h), h_ref, rtol=5e-3,
                               atol=5e-4)


def test_snmf_sparsity_increases_with_beta():
    a, w0, h0 = _problem(seed=23, m=80, n=30)

    def zero_frac(beta):
        cfg = SolverConfig(algorithm="snmf", max_iter=300,
                           sparsity_beta=beta)
        res = solve(jnp.asarray(a, jnp.float32),
                    jnp.asarray(w0, jnp.float32),
                    jnp.asarray(h0, jnp.float32), cfg)
        assert np.isfinite(float(res.dnorm))
        return float((np.asarray(res.h) < 1e-6).mean())

    assert zero_frac(1.0) > zero_frac(0.0)


def test_snmf_config_validation():
    import pytest

    with pytest.raises(ValueError, match="sparsity_beta"):
        SolverConfig(algorithm="snmf", sparsity_beta=-0.1)
    with pytest.raises(ValueError, match="ridge_eta"):
        SolverConfig(algorithm="snmf", ridge_eta=-1.0)
