"""Per-rule nmfx-lint tests: every rule must flag its known-bad fixture
and stay quiet on a minimal clean twin (ISSUE 3 acceptance: mutating a
SolverConfig field out of the fingerprint, or adding an unsplit key
reuse, turns the corresponding test red).

The AST rules run over tmp-file fixtures through the real ``run()``
driver (suppression machinery included); NMFX001 tests drive the pure
``check_config_coverage`` with mutated field universes; the jaxpr-layer
tests feed deliberately-bad traced functions to ``check_engine_jaxpr``.
"""

import json
import subprocess
import sys
import textwrap

import pytest

from nmfx.analysis import active, run
from nmfx.analysis.rules_config import check_config_coverage


def _lint(tmp_path, source, rules, jaxpr=False, name="fixture.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return run([str(path)], jaxpr=jaxpr, rule_ids=rules)


def _ids(findings):
    return [f.rule_id for f in active(findings)]


# ---------------------------------------------------------------- NMFX001

def _universe(**overrides):
    """A minimal healthy config universe; overrides inject the defect."""
    base = dict(
        solver_fields=frozenset({"algorithm", "tol_x", "restart_chunk",
                                 "experimental"}),
        experimental_fields=frozenset({"ragged"}),
        fingerprint_covered=frozenset({"algorithm", "tol_x",
                                       "experimental"}),
        fingerprint_excluded=("restart_chunk",),
        declared_non_numerics=("restart_chunk",),
        exec_key_covered=frozenset({"algorithm", "tol_x", "restart_chunk",
                                    "experimental"}),
        persist_key_covered=frozenset({"algorithm", "tol_x",
                                       "restart_chunk", "experimental"}),
        hashable_configs={"SolverConfig": True, "ExperimentalConfig": True},
    )
    base.update(overrides)
    return base


def test_nmfx001_clean_universe_quiet():
    assert check_config_coverage(**_universe()) == []


def test_nmfx001_live_tree_clean():
    """The REAL config/registry/exec_cache triple passes — the
    introspection hooks agree with the dataclasses."""
    from nmfx.analysis.rules_config import _live_universe

    assert check_config_coverage(**_live_universe()) == []


def test_nmfx001_field_dropped_from_fingerprint_fires():
    """The acceptance-criteria mutation: a numerics-affecting field
    (tol_x) that stops reaching the fingerprint is an error."""
    problems = check_config_coverage(**_universe(
        fingerprint_covered=frozenset({"algorithm", "experimental"})))
    assert any("tol_x" in p and "fingerprint" in p for p in problems)


def test_nmfx001_undeclared_exclusion_fires():
    """Excluding a field without declaring it non-numerics is an error
    even if someone ALSO forgot it in NON_NUMERICS_FIELDS."""
    problems = check_config_coverage(**_universe(
        fingerprint_excluded=("restart_chunk", "tol_x"),
        fingerprint_covered=frozenset({"algorithm", "experimental"})))
    assert any("tol_x" in p and "NON_NUMERICS_FIELDS" in p
               for p in problems)


def test_nmfx001_stale_declaration_fires():
    problems = check_config_coverage(**_universe(
        declared_non_numerics=("restart_chunk", "gone_field")))
    assert any("gone_field" in p and "stale" in p for p in problems)


def test_nmfx001_stale_resolved_declaration_fires():
    """FINGERPRINT_SOLVER_RESOLVED naming a non-field is an error (the
    constant is load-bearing: _fingerprint iterates it)."""
    problems = check_config_coverage(**_universe(
        fingerprint_resolved=("gone_field",)))
    assert any("gone_field" in p and "RESOLVED" in p for p in problems)


def test_nmfx001_exec_key_gap_fires():
    """A field invisible to the exec-cache bucket key (e.g. added with
    compare=False) shares one executable across different configs."""
    problems = check_config_coverage(**_universe(
        exec_key_covered=frozenset({"algorithm", "restart_chunk",
                                    "experimental"})))
    assert any("tol_x" in p and "bucket key" in p for p in problems)


def test_nmfx001_persist_key_gap_fires():
    """A field missing from the PERSISTENT disk key (e.g. declared
    repr=False — present in the in-memory key's hash but invisible in
    its repr) would serve one on-disk executable to configs that should
    persist separately."""
    problems = check_config_coverage(**_universe(
        persist_key_covered=frozenset({"algorithm", "restart_chunk",
                                       "experimental"})))
    assert any("tol_x" in p and "persistent" in p for p in problems)
    # the in-memory key is intact, so only the persistent check fires
    assert not any("solver_key_fields" in p for p in problems)


def test_nmfx001_nested_nonrepr_field_fires():
    """A repr=False field — even on the NESTED ExperimentalConfig, which
    the SolverConfig-level persist hook cannot see — vanishes from the
    repr-derived disk key while staying in the in-memory hash/eq key: a
    fresh process would deserialize the wrong executable."""
    problems = check_config_coverage(**_universe(
        nonrepr_fields={"ExperimentalConfig": ("hidden",)}))
    assert any("ExperimentalConfig.hidden" in p and "repr=False" in p
               for p in problems)


def test_nmfx001_persist_key_check_skipped_when_not_provided():
    """Callers without a persist hook (pre-persistence universes) are
    not retroactively flagged — the check activates only when the
    universe declares persistent coverage."""
    u = _universe()
    u.pop("persist_key_covered")
    assert check_config_coverage(**u) == []


def test_nmfx001_unhashable_config_fires():
    problems = check_config_coverage(**_universe(
        hashable_configs={"SolverConfig": False,
                          "ExperimentalConfig": True}))
    assert any("SolverConfig" in p and "hashable" in p for p in problems)


def test_nmfx001_noncompare_field_fires():
    """A compare=False field — even on the NESTED ExperimentalConfig —
    is invisible to dataclass hash/eq and so to the bucket key."""
    problems = check_config_coverage(**_universe(
        noncompare_fields={"ExperimentalConfig": ("sneaky",)}))
    assert any("ExperimentalConfig.sneaky" in p
               and "compare=False" in p for p in problems)


def test_nmfx001_data_key_gap_fires():
    """A DataKey field dropped from the input-cache key (compare=False)
    would serve ONE resident device buffer to two placements that must
    differ — the data-plane twin of the executable-key hazards."""
    problems = check_config_coverage(**_universe(
        data_fields=frozenset({"fingerprint", "shape", "dtype"}),
        data_key_covered=frozenset({"fingerprint", "shape"})))
    assert any("DataKey.dtype" in p and "input-cache" in p
               for p in problems)


def test_nmfx001_data_key_covered_quiet():
    problems = check_config_coverage(**_universe(
        data_fields=frozenset({"fingerprint", "shape"}),
        data_key_covered=frozenset({"fingerprint", "shape"})))
    assert problems == []


def test_nmfx001_data_key_check_skipped_when_not_provided():
    """Pre-data-cache universes are not retroactively flagged."""
    assert check_config_coverage(**_universe(
        data_fields=frozenset({"fingerprint"}))) == []


def test_nmfx001_serve_key_gap_fires():
    """The acceptance mutation for the serving front-end: a ServeConfig
    field dropped from the policy fingerprint (added compare=False)
    would alias two different admission/packing policies."""
    problems = check_config_coverage(**_universe(
        serve_fields=frozenset({"max_queue_depth", "pack",
                                "batch_linger_s"}),
        serve_key_covered=frozenset({"max_queue_depth", "pack"})))
    assert any("ServeConfig.batch_linger_s" in p
               and "serve_key_fields" in p for p in problems)


def test_nmfx001_serve_key_covered_quiet():
    problems = check_config_coverage(**_universe(
        serve_fields=frozenset({"max_queue_depth", "pack"}),
        serve_key_covered=frozenset({"max_queue_depth", "pack"})))
    assert problems == []


def test_nmfx001_serve_key_check_skipped_when_not_provided():
    """Pre-serve universes are not retroactively flagged."""
    assert check_config_coverage(**_universe(
        serve_fields=frozenset({"max_queue_depth"}))) == []


def test_nmfx001_autotune_key_gap_fires():
    """The round-7 acceptance mutation: a config field outside both the
    autotune store key AND the declared tunable exemptions would let a
    shape tuned under one value be served to the other."""
    problems = check_config_coverage(**_universe(
        autotune_solver_covered=frozenset({"algorithm", "experimental"}),
        autotune_experimental_covered=frozenset({"ragged"}),
        autotune_exempt_solver=("restart_chunk",)))
    assert any("tol_x" in p and "autotune store key" in p
               for p in problems)


def test_nmfx001_autotune_experimental_gap_fires():
    problems = check_config_coverage(**_universe(
        autotune_solver_covered=frozenset({"algorithm", "tol_x",
                                           "restart_chunk",
                                           "experimental"}),
        autotune_experimental_covered=frozenset()))
    assert any("ExperimentalConfig.ragged" in p
               and "autotune store key" in p for p in problems)


def test_nmfx001_autotune_stale_exemption_fires():
    """AUTOTUNE_EXEMPT_* naming a non-field is a stale declaration (a
    renamed tunable would silently join the key and split it)."""
    problems = check_config_coverage(**_universe(
        autotune_solver_covered=frozenset({"algorithm", "tol_x",
                                           "restart_chunk",
                                           "experimental"}),
        autotune_experimental_covered=frozenset({"ragged"}),
        autotune_exempt_solver=("gone_knob",)))
    assert any("gone_knob" in p and "stale" in p for p in problems)


def test_nmfx001_autotune_contradictory_declaration_fires():
    """A field both exempt (tunable) and in the key could never be
    applied — the entry's verdict for it would always be masked by the
    key split."""
    problems = check_config_coverage(**_universe(
        autotune_solver_covered=frozenset({"algorithm", "tol_x",
                                           "restart_chunk",
                                           "experimental"}),
        autotune_experimental_covered=frozenset({"ragged"}),
        autotune_exempt_solver=("tol_x",)))
    assert any("tol_x" in p and "drop one declaration" in p
               for p in problems)


def test_nmfx001_autotune_clean_twin_quiet():
    problems = check_config_coverage(**_universe(
        autotune_solver_covered=frozenset({"algorithm", "tol_x",
                                           "experimental"}),
        autotune_experimental_covered=frozenset({"ragged"}),
        autotune_exempt_solver=("restart_chunk",)))
    assert problems == []


def test_nmfx001_live_serve_config_covered():
    """The REAL ServeConfig: every field participates in comparison
    (serve_key_fields == the full field set), so the live tree stays
    lint-clean."""
    import dataclasses

    from nmfx import serve

    assert serve.serve_key_fields() == frozenset(
        f.name for f in dataclasses.fields(serve.ServeConfig))


# ---------------------------------------------------------------- NMFX002

_ENV_BAD = """
    import os
    import jax

    @jax.jit
    def solve(x):
        return x * _scale()

    def _scale():
        return float(os.environ.get("NMFX_SCALE", "1"))
"""

_ENV_CLEAN = """
    import os
    import jax

    _SCALE = float(os.environ.get("NMFX_SCALE", "1"))  # import time: fine

    @jax.jit
    def solve(x):
        return x * _SCALE
"""


def test_nmfx002_env_read_reachable_from_jit(tmp_path):
    assert _ids(_lint(tmp_path, _ENV_BAD, ["NMFX002"])) == ["NMFX002"]


def test_nmfx002_import_time_read_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _ENV_CLEAN, ["NMFX002"])) == []


def test_nmfx002_aliased_spellings(tmp_path):
    """`import os as _os` / `from os import getenv` / `from os import
    environ` are the same hazard — resolution goes through the
    module's imports, not literal text."""
    for body in (
        "import os as _os\n\n@jax.jit\ndef f(x):\n"
        "    return x * float(_os.environ.get('S', '1'))\n",
        "from os import getenv\n\n@jax.jit\ndef f(x):\n"
        "    return x * float(getenv('S', '1'))\n",
        "from os import environ\n\n@jax.jit\ndef f(x):\n"
        "    return x * float(environ['S'])\n",
    ):
        src = "import jax\n" + body
        assert _ids(_lint(tmp_path, src, ["NMFX002"])) == ["NMFX002"], src


def test_suppression_in_string_literal_inert(tmp_path):
    """Suppression syntax quoted inside a string literal neither
    suppresses nor trips NMFX000 — only real comments count."""
    src = _ENV_BAD + (
        '    _DOC = "example:  # nmfx: ignore[NMFX002]"\n')
    findings = _lint(tmp_path, src, ["NMFX002"])
    ids = _ids(findings)
    assert ids == ["NMFX002"]  # the env read; NO NMFX000 for the string


def test_nmfx002_suppression_with_reason(tmp_path):
    src = _ENV_BAD.replace(
        'return float(os.environ.get("NMFX_SCALE", "1"))',
        'return float(os.environ.get("NMFX_SCALE", "1"))'
        '  # nmfx: ignore[NMFX002] -- fixture exercising suppressions')
    findings = _lint(tmp_path, src, ["NMFX002"])
    assert _ids(findings) == []
    assert any(f.suppressed for f in findings)


def test_nmfx000_suppression_without_reason_is_a_finding(tmp_path):
    src = _ENV_BAD.replace(
        'return float(os.environ.get("NMFX_SCALE", "1"))',
        'return float(os.environ.get("NMFX_SCALE", "1"))'
        '  # nmfx: ignore[NMFX002]')
    findings = _lint(tmp_path, src, ["NMFX002"])
    ids = _ids(findings)
    assert "NMFX000" in ids  # the malformed comment itself
    assert "NMFX002" in ids  # and it suppressed nothing


# ---------------------------------------------------------------- NMFX003

_DONATE_BAD = """
    import jax

    def serve(w, h):
        step = jax.jit(_update, donate_argnums=(0,))
        w2 = step(w)
        return w + w2  # read of donated w
"""

_DONATE_CLEAN = """
    import jax

    def serve(w, h):
        step = jax.jit(_update, donate_argnums=(0,))
        w = step(w)  # rebind: the donated name dies with the old binding
        return w + h
"""

_ALIAS_BAD = """
    import jax
    from jax.experimental import pallas as pl

    def launch(kernel, wbuf, out_shape):
        run = pl.pallas_call(kernel, out_shape=out_shape,
                             input_output_aliases={0: 0})
        result = run(wbuf)
        checksum = wbuf.sum()  # wbuf is dead
        return result, checksum
"""


def test_nmfx003_read_after_donate(tmp_path):
    findings = _lint(tmp_path, _DONATE_BAD, ["NMFX003"])
    assert _ids(findings) == ["NMFX003"]
    assert "donated" in findings[0].message


def test_nmfx003_rebind_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _DONATE_CLEAN, ["NMFX003"])) == []


def test_nmfx003_pallas_alias(tmp_path):
    findings = _lint(tmp_path, _ALIAS_BAD, ["NMFX003"])
    assert _ids(findings) == ["NMFX003"]
    assert "wbuf" in findings[0].message


def test_nmfx003_donate_argnames(tmp_path):
    """String donate_argnames track too: keyword args by name, and the
    common positional idiom where the variable carries the parameter
    name."""
    src = """
        import jax

        def serve(w, h):
            step = jax.jit(_update, donate_argnames=("w",))
            w2 = step(w)
            return w + w2  # read of donated w
    """
    findings = _lint(tmp_path, src, ["NMFX003"])
    assert _ids(findings) == ["NMFX003"]
    assert "'w'" in findings[0].message
    kw = src.replace("step(w)", "step(w=w)")
    assert _ids(_lint(tmp_path, kw, ["NMFX003"])) == ["NMFX003"]


def test_nmfx003_compound_statement_order(tmp_path):
    """Inside an if/for body, a read that textually PRECEDES the
    donation is legal; a read after it still flags. The compound
    statement's own subtree must not pre-process its children."""
    clean = """
        import jax

        def serve(w, cond):
            g = jax.jit(_update, donate_argnums=(0,))
            if cond:
                u = w + 1  # read BEFORE the donation: fine
                r = g(w)
                return r + u
            return w
    """
    assert _ids(_lint(tmp_path, clean, ["NMFX003"])) == []

    bad = """
        import jax

        def serve(w, cond):
            g = jax.jit(_update, donate_argnums=(0,))
            if cond:
                r = g(w)
                u = w + 1  # read AFTER the donation
                return r + u
            return w
    """
    findings = _lint(tmp_path, bad, ["NMFX003"])
    assert _ids(findings) == ["NMFX003"]
    assert "'w'" in findings[0].message


def test_nmfx003_partial_factory(tmp_path):
    """partial-spelled jit: the factory's function argument is NOT a
    donated buffer, but a buffer passed through the factory-built
    callable IS tracked (the real round-3 hazard shape)."""
    src = """
        import functools
        import jax

        def serve(w, h):
            mk = functools.partial(jax.jit, donate_argnums=(0,))
            step = mk(_update)
            w2 = step(w)
            return w + w2  # read of donated w
    """
    findings = _lint(tmp_path, src, ["NMFX003"])
    assert len(_ids(findings)) == 1
    assert "'w'" in findings[0].message  # w, not _update

    clean = src.replace("w2 = step(w)\n            return w + w2"
                        "  # read of donated w",
                        "w = step(w)\n            return w + h")
    assert _ids(_lint(tmp_path, clean, ["NMFX003"])) == []


# ---------------------------------------------------------------- NMFX004

_KEY_REUSE_BAD = """
    import jax

    def init_factors(key, m, n, k):
        w0 = jax.random.uniform(key, (m, k))
        h0 = jax.random.uniform(key, (k, n))  # same key: correlated
        return w0, h0
"""

_KEY_REUSE_CLEAN = """
    import jax

    def init_factors(key, m, n, k):
        kw, kh = jax.random.split(key)
        w0 = jax.random.uniform(kw, (m, k))
        h0 = jax.random.uniform(kh, (k, n))
        return w0, h0
"""

_HOST_RNG_BAD = """
    import jax
    import numpy as np

    @jax.jit
    def noisy_step(x):
        return x + np.random.normal()  # frozen at trace time
"""


def test_nmfx004_key_reuse(tmp_path):
    findings = _lint(tmp_path, _KEY_REUSE_BAD, ["NMFX004"])
    assert _ids(findings) == ["NMFX004"]
    assert "key" in findings[0].message


def test_nmfx004_split_idiom_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _KEY_REUSE_CLEAN, ["NMFX004"])) == []


def test_nmfx004_fold_in_threading_quiet(tmp_path):
    """The canonical key-threading idiom rebinds the name between
    consumptions — a store resurrects the key, so this is NOT reuse."""
    src = """
        import jax

        def chain(key, m):
            x = jax.random.uniform(key, (m,))
            key = jax.random.fold_in(key, 1)
            y = jax.random.normal(key, (m,))
            return x + y
    """
    assert _ids(_lint(tmp_path, src, ["NMFX004"])) == []


def test_nmfx004_loop_carried_reuse(tmp_path):
    """A key consumed inside a loop without per-iteration rebinding
    replays the identical draw every trip; the fold_in-per-iteration
    idiom stays quiet."""
    bad = """
        import jax

        def restarts(key, m, k, r):
            out = []
            for i in range(r):
                out.append(jax.random.uniform(key, (m, k)))
            return out
    """
    findings = _lint(tmp_path, bad, ["NMFX004"])
    assert _ids(findings) == ["NMFX004"]
    assert "loop" in findings[0].message

    clean = """
        import jax

        def restarts(key, m, k, r):
            out = []
            for i in range(r):
                ki = jax.random.fold_in(key, i)
                out.append(jax.random.uniform(ki, (m, k)))
            return out
    """
    assert _ids(_lint(tmp_path, clean, ["NMFX004"])) == []


def test_nmfx004_nested_loop_single_finding(tmp_path):
    """One defect, one finding: the inner loop's own pass owns a
    consumption nested two loops deep."""
    src = """
        import jax

        def grid(key, r):
            for i in range(r):
                for j in range(2):
                    x = jax.random.uniform(key, (3,))
            return x
    """
    findings = _lint(tmp_path, src, ["NMFX004"])
    assert len(_ids(findings)) == 1


def test_nmfx004_branchlocal_consumption_quiet(tmp_path):
    """Sibling branches each consume the key once — no path consumes
    it twice, so nothing flags."""
    src = """
        import jax

        def pick(key, m, flip):
            if flip:
                return jax.random.uniform(key, (m,))
            else:
                return jax.random.normal(key, (m,))
    """
    assert _ids(_lint(tmp_path, src, ["NMFX004"])) == []


def test_nmfx004_host_rng_in_traced(tmp_path):
    findings = _lint(tmp_path, _HOST_RNG_BAD, ["NMFX004"])
    assert _ids(findings) == ["NMFX004"]
    assert "trace" in findings[0].message


def test_nmfx004_host_rng_aliased_numpy(tmp_path):
    """`import numpy as onp` / `from numpy import random as nprand`
    are the same host-RNG hazard — resolved through the module's
    imports like NMFX002 does for os."""
    onp = _HOST_RNG_BAD.replace("import numpy as np",
                                "import numpy as onp"
                                ).replace("np.random.normal()",
                                          "onp.random.normal()")
    assert _ids(_lint(tmp_path, onp, ["NMFX004"])) == ["NMFX004"]
    nprand = _HOST_RNG_BAD.replace(
        "import numpy as np", "from numpy import random as nprand"
    ).replace("np.random.normal()", "nprand.normal()")
    assert _ids(_lint(tmp_path, nprand, ["NMFX004"])) == ["NMFX004"]


def test_nmfx004_stdlib_random_not_a_key(tmp_path):
    """stdlib `random.shuffle(data)` twice on one sequence is NOT key
    reuse — only jax.random consumption counts (base resolved through
    the module's imports)."""
    src = """
        import random

        def shuffle_twice(data):
            random.shuffle(data)
            picked = random.sample(data, 3)
            return picked
    """
    assert _ids(_lint(tmp_path, src, ["NMFX004"])) == []


def test_nmfx004_from_jax_import_random_is_keys(tmp_path):
    """`from jax import random; random.uniform(key...)` twice IS key
    reuse — and is not misflagged as host RNG."""
    src = """
        from jax import random

        def init(key, m, k):
            w = random.uniform(key, (m, k))
            h = random.uniform(key, (k, m))
            return w, h
    """
    findings = _lint(tmp_path, src, ["NMFX004"])
    assert _ids(findings) == ["NMFX004"]
    assert "key" in findings[0].message and "consumed" in findings[0].message


# ---------------------------------------------------------------- NMFX005

_SYNC_BAD = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def residual(a, w, h):
        r = jnp.linalg.norm(a - w @ h)
        return float(r)  # host sync on a traced value
"""

_SYNC_CLEAN = """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def residual(a, w, h):
        n_scale = float(a.shape[0] * a.shape[1])  # static host math: fine
        return jnp.linalg.norm(a - w @ h) / n_scale
"""


def test_nmfx005_host_sync_in_traced(tmp_path):
    findings = _lint(tmp_path, _SYNC_BAD, ["NMFX005"])
    assert _ids(findings) == ["NMFX005"]


def test_nmfx005_static_shape_math_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _SYNC_CLEAN, ["NMFX005"])) == []


def test_nmfx005_item_call(tmp_path):
    src = _SYNC_BAD.replace("return float(r)", "return r.item()")
    findings = _lint(tmp_path, src, ["NMFX005"])
    assert _ids(findings) == ["NMFX005"]
    assert ".item()" in findings[0].message


# ---------------------------------------------------------------- NMFX006

_HANDLER_BAD = """
    def fetch(cache, key):
        try:
            return cache[key].load()
        except Exception:
            return None  # silent degradation: nobody will ever know
"""

_HANDLER_CLEAN_RERAISE = """
    class TypedError(RuntimeError):
        pass

    def fetch(cache, key):
        try:
            return cache[key].load()
        except Exception as e:
            raise TypedError("load failed") from e
"""

_HANDLER_CLEAN_FUTURE = """
    def resolve(fut, work):
        try:
            fut.set_result(work())
        except BaseException as e:
            fut.set_exception(e)
"""

_HANDLER_CLEAN_WARN = """
    from nmfx.faults import warn_once

    def fetch(cache, key, fallback):
        try:
            return cache[key].load()
        except Exception as e:
            warn_once("cache-fallback", f"degraded ({e!r})")
            return fallback()
"""

_HANDLER_CLEAN_NARROW = """
    def fetch(cache, key):
        try:
            return cache[key].load()
        except KeyError:
            return None  # narrow: a considered, specific decision
"""


def test_nmfx006_silent_swallow_fires(tmp_path):
    findings = _lint(tmp_path, _HANDLER_BAD, ["NMFX006"])
    assert _ids(findings) == ["NMFX006"]
    assert "except Exception" in findings[0].message


def test_nmfx006_bare_except_fires(tmp_path):
    src = _HANDLER_BAD.replace("except Exception:", "except:")
    findings = _lint(tmp_path, src, ["NMFX006"])
    assert _ids(findings) == ["NMFX006"]
    assert "bare except" in findings[0].message


def test_nmfx006_broad_in_tuple_fires(tmp_path):
    src = _HANDLER_BAD.replace("except Exception:",
                               "except (KeyError, Exception):")
    assert _ids(_lint(tmp_path, src, ["NMFX006"])) == ["NMFX006"]


def test_nmfx006_reraise_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _HANDLER_CLEAN_RERAISE,
                      ["NMFX006"])) == []


def test_nmfx006_future_resolution_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _HANDLER_CLEAN_FUTURE,
                      ["NMFX006"])) == []


def test_nmfx006_warn_once_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _HANDLER_CLEAN_WARN, ["NMFX006"])) == []


def test_nmfx006_scoped_warn_once_variant_quiet(tmp_path):
    """An instance-level warn-once helper (ExecCache._warn_once) is the
    same loudness contract with narrower dedup scope — compliant."""
    src = _HANDLER_CLEAN_WARN.replace(
        "from nmfx.faults import warn_once\n", "").replace(
        'warn_once("cache-fallback"', 'cache._warn_once("cache-fallback"')
    assert _ids(_lint(tmp_path, src, ["NMFX006"])) == []


def test_nmfx006_narrow_handler_quiet(tmp_path):
    assert _ids(_lint(tmp_path, _HANDLER_CLEAN_NARROW,
                      ["NMFX006"])) == []


def test_nmfx006_nested_def_does_not_count(tmp_path):
    """A warn_once inside a callback DEFINED in the handler runs later
    — it is not this handler's disposal, so the handler still fires."""
    src = """
        from nmfx.faults import warn_once

        def fetch(cache, key):
            try:
                return cache[key].load()
            except Exception as e:
                def later():
                    warn_once("cache", f"degraded ({e!r})")
                return later
    """
    assert _ids(_lint(tmp_path, src, ["NMFX006"])) == ["NMFX006"]


def test_nmfx006_suppression_with_reason(tmp_path):
    src = _HANDLER_BAD.replace(
        "except Exception:",
        "except Exception:  # nmfx: ignore[NMFX006] -- best-effort")
    findings = _lint(tmp_path, src, ["NMFX006"])
    assert _ids(findings) == []  # suppressed findings are not active
    assert any(f.rule_id == "NMFX006" and f.suppressed
               for f in findings)


# ----------------------------------------------------------- jaxpr layer

def test_jaxpr_f64_leak_detected():
    """An np.float64 constant leaking into f32 math is invisible under
    the normal session but explodes to f64 under the x64 parity config —
    NMFX101's check sees the convert/aval in the jaxpr."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nmfx.analysis.jaxpr_rules import check_engine_jaxpr

    try:
        ctx = jax.experimental.enable_x64(True)
    except AttributeError:
        pytest.skip("jax.experimental.enable_x64 unavailable")
    with ctx:
        bad = jax.make_jaxpr(
            lambda x: x * np.float64(2.0))(
                jax.ShapeDtypeStruct((4,), jnp.float32))
        clean = jax.make_jaxpr(
            lambda x: x * 2.0)(jax.ShapeDtypeStruct((4,), jnp.float32))
    assert any("float64" in p for p in check_engine_jaxpr("bad", bad))
    assert check_engine_jaxpr("clean", clean) == []


def test_jaxpr_device_put_in_loop_detected():
    import jax
    import jax.numpy as jnp

    from nmfx.analysis.jaxpr_rules import check_engine_jaxpr

    def bad(x):
        def body(c):
            return jax.device_put(c) + 1.0

        return jax.lax.while_loop(lambda c: c[0] < 3.0, body, x)

    def clean(x):
        return jax.lax.while_loop(lambda c: c[0] < 3.0,
                                  lambda c: c + 1.0, x)

    jx_bad = jax.make_jaxpr(bad)(jax.ShapeDtypeStruct((2,), jnp.float32))
    jx_clean = jax.make_jaxpr(clean)(
        jax.ShapeDtypeStruct((2,), jnp.float32))
    assert any("device_put" in p
               for p in check_engine_jaxpr("bad", jx_bad))
    assert check_engine_jaxpr("clean", jx_clean) == []


def test_jaxpr_registered_engines_trace_clean():
    """Every registered engine traces abstractly under the x64 parity
    config with no f64 leak and no loop-body device_put — the static
    form of the x64-parity/transfer-overlap contracts (this is the test
    that caught the StopReason-IntEnum int64 carry poisoning)."""
    from nmfx.analysis.jaxpr_rules import run_jaxpr_checks

    assert run_jaxpr_checks() == []


# ----------------------------------------------------------------- CLI

def test_cli_json_output(tmp_path):
    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    proc = subprocess.run(
        [sys.executable, "-m", "nmfx.analysis", str(path), "--json",
         "--no-jaxpr", "--rules", "NMFX002"],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["ok"] is False
    assert doc["summary"]["errors"] == 1
    assert doc["findings"][0]["rule_id"] == "NMFX002"


def test_nmfx102_rule_selectable():
    """``--rules NMFX102`` must run the device_put check on its own (the
    jaxpr results are shared between NMFX101/NMFX102 but each rule is
    registered and filterable separately)."""
    from nmfx.analysis import RULES

    assert "NMFX101" in RULES and "NMFX102" in RULES
    from nmfx.analysis.ast_scan import Project
    from nmfx.analysis.jaxpr_rules import _project_jaxpr_results

    project = Project([])
    project.jaxpr_checks_enabled = True
    project._jaxpr_results = [
        ("fake", "NMFX102", "fake: device_put inside a while body"),
        ("fake", "NMFX101", "fake: f64 leak"),
    ]
    f102 = list(RULES["NMFX102"].check(project))
    f101 = list(RULES["NMFX101"].check(project))
    assert [f.rule_id for f in f102] == ["NMFX102"]
    assert [f.rule_id for f in f101] == ["NMFX101"]
    assert _project_jaxpr_results(project) is project._jaxpr_results


def test_cli_nonexistent_path_fails(tmp_path):
    """A typo'd lint target must fail the run (exit 2), never report
    '0 errors' while linting nothing."""
    proc = subprocess.run(
        [sys.executable, "-m", "nmfx.analysis",
         str(tmp_path / "no_such_dir"), "--no-jaxpr"],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 2
    assert "no_such_dir" in proc.stderr


def test_baseline_path_normalization(tmp_path):
    """A baseline recorded with one path spelling applies to a run
    invoked with another (relative vs absolute), same cwd."""
    import os

    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    findings = run([str(path)], jaxpr=False, rule_ids=["NMFX002"])
    baseline = tmp_path / "baseline.json"
    rel = os.path.relpath(str(path))
    baseline.write_text(json.dumps(
        [{"file": rel, "rule": f.rule_id, "line": f.line}
         for f in active(findings)]))
    rebaselined = run([str(path)], baseline=str(baseline), jaxpr=False,
                      rule_ids=["NMFX002"])
    assert _ids(rebaselined) == []


def test_cli_baseline_tolerates(tmp_path):
    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    findings = run([str(path)], jaxpr=False, rule_ids=["NMFX002"])
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps(
        [{"file": f.file, "rule": f.rule_id, "line": f.line}
         for f in active(findings)]))
    rebaselined = run([str(path)], baseline=str(baseline), jaxpr=False,
                      rule_ids=["NMFX002"])
    assert _ids(rebaselined) == []
    assert any(f.baselined for f in rebaselined)


def test_cli_write_baseline_refresh_keeps_records(tmp_path):
    """--write-baseline together with --baseline (the refresh idiom)
    must re-record tolerated findings, not truncate to []."""
    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    baseline = tmp_path / "baseline.json"
    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "nmfx.analysis", str(path), "--no-jaxpr",
         "--rules", "NMFX002", "--write-baseline", str(baseline)],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 0
    first = json.loads(baseline.read_text())
    assert len(first) == 1
    proc = subprocess.run(
        [sys.executable, "-m", "nmfx.analysis", str(path), "--no-jaxpr",
         "--rules", "NMFX002", "--baseline", str(baseline),
         "--write-baseline", str(baseline)],
        capture_output=True, text=True, timeout=240, env=env)
    assert proc.returncode == 0
    assert json.loads(baseline.read_text()) == first


def _update_baseline(path, baseline, env):
    return subprocess.run(
        [sys.executable, "-m", "nmfx.analysis", str(path), "--no-jaxpr",
         "--rules", "NMFX002", "--update-baseline", str(baseline)],
        capture_output=True, text=True, timeout=240, env=env)


def test_cli_update_baseline_round_trip_byte_stable(tmp_path):
    """--update-baseline regenerates in place; a second run with no
    source change reproduces the file BYTE for byte (the property that
    keeps baseline refreshes out of code review noise), and recorded
    'reason' fields survive the regeneration — including when the
    finding moved lines."""
    import os

    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    baseline = tmp_path / "lint_baseline.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    proc = _update_baseline(path, baseline, env)
    assert proc.returncode == 0, proc.stderr
    first = baseline.read_bytes()
    records = json.loads(first)
    assert len(records) == 1 and records[0]["reason"] == ""
    assert "lack a 'reason'" in proc.stdout

    # a human records the required reason; regeneration keeps it
    records[0]["reason"] = "trace-time read audited 2026-08"
    baseline.write_text(json.dumps(records, indent=2) + "\n")
    proc = _update_baseline(path, baseline, env)
    assert proc.returncode == 0
    again = json.loads(baseline.read_text())
    assert again[0]["reason"] == "trace-time read audited 2026-08"
    assert "lack a 'reason'" not in proc.stdout

    # byte-stable round trip from here on
    stable = baseline.read_bytes()
    proc = _update_baseline(path, baseline, env)
    assert proc.returncode == 0
    assert baseline.read_bytes() == stable

    # the finding moves a line: reason follows via the (file, rule)
    # fallback instead of resetting to ""
    path.write_text("\n" + path.read_text())
    proc = _update_baseline(path, baseline, env)
    assert proc.returncode == 0
    moved = json.loads(baseline.read_text())
    assert moved[0]["line"] == records[0]["line"] + 1
    assert moved[0]["reason"] == "trace-time read audited 2026-08"


def test_cli_update_baseline_drops_fixed_findings(tmp_path):
    """A fixed finding leaves the baseline on refresh — tolerated debt
    does not outlive the code it tolerated."""
    import os

    path = tmp_path / "bad.py"
    path.write_text(textwrap.dedent(_ENV_BAD))
    baseline = tmp_path / "lint_baseline.json"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    assert _update_baseline(path, baseline, env).returncode == 0
    assert len(json.loads(baseline.read_text())) == 1
    path.write_text("x = 1\n")  # the defect is gone
    assert _update_baseline(path, baseline, env).returncode == 0
    assert json.loads(baseline.read_text()) == []


# ---------------------------------------------------------------- NMFX007

def _manifest_universe(**overrides):
    """A minimal healthy checkpoint-manifest universe (the NMFX001
    bad-universe pattern); overrides inject the defect."""
    base = dict(
        solver_fields=frozenset({"algorithm", "tol_x", "restart_chunk"}),
        consensus_fields=frozenset({"restarts", "seed", "label_rule",
                                    "ks", "linkage"}),
        manifest_solver=frozenset({"algorithm", "tol_x"}),
        manifest_consensus=frozenset({"restarts", "seed", "label_rule"}),
        declared_non_numerics=("restart_chunk",),
        manifest_consensus_excluded=("ks", "linkage"),
        declared_checkpoint_exempt=("ks", "linkage"),
    )
    base.update(overrides)
    return base


def test_nmfx007_clean_universe_quiet():
    from nmfx.analysis.rules_config import check_manifest_coverage

    assert check_manifest_coverage(**_manifest_universe()) == []


def test_nmfx007_live_tree_clean():
    """The shipped tree must satisfy its own manifest-coverage
    contract (the tier-1 zero-findings gate covers the Rule wrapper;
    this pins the pure check on the live universe directly)."""
    from nmfx.analysis.rules_config import (_live_manifest_universe,
                                            check_manifest_coverage)

    assert check_manifest_coverage(**_live_manifest_universe()) == []


def test_nmfx007_solver_field_dropped_from_manifest_fires():
    """A result-affecting SolverConfig field missing from the manifest
    is the stale-resume hazard the rule exists for."""
    from nmfx.analysis.rules_config import check_manifest_coverage

    problems = check_manifest_coverage(**_manifest_universe(
        manifest_solver=frozenset({"algorithm"})))
    assert any("SolverConfig.tol_x" in p and "checkpoint manifest" in p
               for p in problems)


def test_nmfx007_consensus_field_dropped_from_manifest_fires():
    from nmfx.analysis.rules_config import check_manifest_coverage

    problems = check_manifest_coverage(**_manifest_universe(
        manifest_consensus=frozenset({"restarts", "label_rule"})))
    assert any("ConsensusConfig.seed" in p for p in problems)


def test_nmfx007_undeclared_exclusion_fires():
    """Excluding a ConsensusConfig field from the manifest without the
    CHECKPOINT_EXEMPT_FIELDS declaration (and its rationale) fires."""
    from nmfx.analysis.rules_config import check_manifest_coverage

    problems = check_manifest_coverage(**_manifest_universe(
        manifest_consensus=frozenset({"restarts", "label_rule"}),
        manifest_consensus_excluded=("ks", "linkage", "seed")))
    assert any("ConsensusConfig.seed" in p
               and "CHECKPOINT_EXEMPT_FIELDS" in p for p in problems)


def test_nmfx007_stale_exempt_declaration_fires():
    from nmfx.analysis.rules_config import check_manifest_coverage

    problems = check_manifest_coverage(**_manifest_universe(
        declared_checkpoint_exempt=("ks", "linkage", "not_a_field")))
    assert any("not_a_field" in p and "stale" in p for p in problems)


def test_nmfx007_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX007" in RULES


# ---------------------------------------------------------------- NMFX008
# fault-site flight-recorder coverage (ISSUE 10): every registered
# fault site must map to a flight-recorder event category, and no
# mapping entry may go stale. Same pure-check + mutated-universe shape
# as NMFX001/NMFX007; the bad universes below are the fixture pair
# (bad universe fires, clean twin quiet), and the live tree is pinned
# compliant directly.

def _obs_universe(**over):
    base = dict(sites=frozenset({"h2d.transfer", "serve.scheduler"}),
                event_covered=frozenset({"h2d.transfer",
                                         "serve.scheduler"}))
    base.update(over)
    return base


def test_nmfx008_clean_universe_quiet():
    from nmfx.analysis.rules_obs import check_fault_event_coverage

    assert check_fault_event_coverage(**_obs_universe()) == []


def test_nmfx008_live_tree_clean():
    """The shipped tree must satisfy its own coverage contract: every
    site in nmfx.faults.SITES reaches nmfx.obs.flight.FAULT_EVENTS
    (the tier-1 zero-findings gate covers the Rule wrapper; this pins
    the pure check on the live universe directly)."""
    from nmfx.analysis.rules_obs import (_live_universe,
                                         check_fault_event_coverage)

    assert check_fault_event_coverage(**_live_universe()) == []


def test_nmfx008_missing_site_fires():
    """A registered site with no flight-recorder category is the
    silent-postmortem hazard the rule exists for (bad universe)."""
    from nmfx.analysis.rules_obs import check_fault_event_coverage

    problems = check_fault_event_coverage(**_obs_universe(
        event_covered=frozenset({"h2d.transfer"})))
    assert len(problems) == 1
    assert "serve.scheduler" in problems[0]
    assert "FAULT_EVENTS" in problems[0]


def test_nmfx008_stale_mapping_fires():
    """A FAULT_EVENTS entry for an unregistered site is a stale
    declaration (it would mask a site rename)."""
    from nmfx.analysis.rules_obs import check_fault_event_coverage

    problems = check_fault_event_coverage(**_obs_universe(
        event_covered=frozenset({"h2d.transfer", "serve.scheduler",
                                 "old.renamed_site"})))
    assert len(problems) == 1
    assert "old.renamed_site" in problems[0]
    assert "stale" in problems[0]


def test_nmfx008_rule_fires_through_run_on_mutated_mapping(tmp_path,
                                                           monkeypatch):
    """Acceptance mutation: drop a live site's mapping entry and the
    REGISTERED rule (through the real run() path over the real
    faults.py) goes red at the SITES declaration; restore it and the
    run is quiet again."""
    from nmfx import faults as faults_mod
    from nmfx.analysis import run
    from nmfx.obs import flight

    findings = [f for f in run(["nmfx/faults.py"], jaxpr=False,
                               rule_ids=["NMFX008"])
                if f.rule_id == "NMFX008"]
    assert findings == []  # live tree compliant
    broken = dict(flight.FAULT_EVENTS)
    broken.pop("proc.preempt")
    monkeypatch.setattr(flight, "FAULT_EVENTS", broken)
    findings = [f for f in run(["nmfx/faults.py"], jaxpr=False,
                               rule_ids=["NMFX008"])
                if f.rule_id == "NMFX008"]
    assert len(findings) == 1
    assert "proc.preempt" in findings[0].message
    # anchored at the SITES declaration in the analyzed faults.py
    import inspect

    src_lines, decl = inspect.getsourcelines(faults_mod)
    sites_line = next(i for i, line
                      in enumerate(src_lines, start=decl or 1)
                      if line.startswith("SITES ="))
    assert findings[0].line == sites_line


def test_nmfx008_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX008" in RULES


# ---------------------------------------------------------------- NMFX009
# engine-family cost-model coverage (ISSUE 13): every reachable
# (algorithm, engine-family) pair must have a FLOPs+bytes model in
# nmfx.obs.costmodel, the exemption list must stay honest, and no model
# entry may go stale. Same pure-check + mutated-universe shape as
# NMFX001/NMFX007/NMFX008.

def _perf_universe(**over):
    base = dict(
        universe=frozenset({("mu", "packed"), ("mu", "vmap"),
                            ("kl", "vmap")}),
        covered=frozenset({("mu", "packed"), ("mu", "vmap"),
                           ("kl", "vmap")}),
        exempt=("pg",),
        algorithms=frozenset({"mu", "kl", "pg"}))
    base.update(over)
    return base


def test_nmfx009_clean_universe_quiet():
    from nmfx.obs.costmodel import check_costmodel_coverage

    assert check_costmodel_coverage(**_perf_universe()) == []


def test_nmfx009_live_tree_clean():
    """The shipped tree must satisfy its own coverage contract: every
    engine the routing tables can reach has a model (the tier-1
    zero-findings gate covers the Rule wrapper; this pins the pure
    check on the live universe directly)."""
    from nmfx.analysis.rules_perf import _live_universe
    from nmfx.obs.costmodel import check_costmodel_coverage

    assert check_costmodel_coverage(**_live_universe()) == []


def test_nmfx009_missing_model_fires():
    """A reachable engine without a model is the mfu-None blind spot
    the rule exists for (bad universe)."""
    from nmfx.obs.costmodel import check_costmodel_coverage

    problems = check_costmodel_coverage(**_perf_universe(
        covered=frozenset({("mu", "packed"), ("mu", "vmap")})))
    assert len(problems) == 1
    assert "'kl'" in problems[0] and "no cost model" in problems[0]


def test_nmfx009_stale_model_entry_fires():
    from nmfx.obs.costmodel import check_costmodel_coverage

    problems = check_costmodel_coverage(**_perf_universe(
        covered=frozenset({("mu", "packed"), ("mu", "vmap"),
                           ("kl", "vmap"), ("kl", "pallas")})))
    assert len(problems) == 1
    assert "stale entry" in problems[0]


def test_nmfx009_modeled_exempt_fires():
    """An algorithm both exempt and modeled is a contradiction — one
    of the two declarations is rotten."""
    from nmfx.obs.costmodel import check_costmodel_coverage

    problems = check_costmodel_coverage(**_perf_universe(
        covered=frozenset({("mu", "packed"), ("mu", "vmap"),
                           ("kl", "vmap"), ("pg", "vmap")})))
    # fires twice by design: the entry is unreachable (exempt
    # algorithms are outside the universe) AND contradicts the
    # exemption — both messages point at the same rotten declaration
    assert len(problems) == 2
    assert any("COSTMODEL_EXEMPT" in p for p in problems)
    assert any("stale entry" in p for p in problems)


def test_nmfx009_stale_exemption_fires():
    from nmfx.obs.costmodel import check_costmodel_coverage

    problems = check_costmodel_coverage(**_perf_universe(
        exempt=("pg", "ghost")))
    assert len(problems) == 1
    assert "'ghost'" in problems[0]


def test_nmfx009_rule_fires_on_mutated_live_table(monkeypatch):
    """End-to-end through the Rule wrapper: dropping a live model
    entry turns the tree red, anchored at the _FLOPS declaration in
    the analyzed costmodel.py."""
    from nmfx.obs import costmodel as cm_mod

    target = ["nmfx/obs/costmodel.py"]
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX009"])
                if f.rule_id == "NMFX009"]
    assert findings == []  # live tree compliant
    broken = dict(cm_mod._FLOPS)
    broken.pop(("snmf", "packed"))
    monkeypatch.setattr(cm_mod, "_FLOPS", broken)
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX009"])
                if f.rule_id == "NMFX009"]
    assert len(findings) == 1
    assert "'snmf'" in findings[0].message
    import inspect

    src_lines, decl = inspect.getsourcelines(cm_mod)
    flops_line = next(i for i, line
                      in enumerate(src_lines, start=decl or 1)
                      if line.startswith("_FLOPS ="))
    assert findings[0].line == flops_line


def test_nmfx009_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX009" in RULES


# ---------------------------------------------------------------- NMFX010
# registry metric naming + docs-table coverage (ISSUE 14): every live
# nmfx_* metric must match the nmfx_<subsystem>_<what>[_<unit>] scheme
# (counters end _total), appear in docs/observability.md's metric
# table, and no documented row may go stale. Same pure-check +
# mutated-universe shape as NMFX008/NMFX009.

def _metric_universe(**over):
    base = dict(
        live={"nmfx_serve_dispatches_total": "counter",
              "nmfx_serve_queue_wait_seconds": "histogram",
              "nmfx_serve_queue_depth": "gauge"},
        documented=frozenset({"nmfx_serve_dispatches_total",
                              "nmfx_serve_queue_wait_seconds",
                              "nmfx_serve_queue_depth"}))
    base.update(over)
    return base


def test_nmfx010_clean_universe_quiet():
    from nmfx.analysis.rules_obs import check_metric_naming

    assert check_metric_naming(**_metric_universe()) == []


def test_nmfx010_live_tree_clean():
    """The shipped tree must satisfy its own namespace contract: every
    live nmfx_* metric is scheme-clean and documented, and every docs
    row is live (the tier-1 zero-findings gate covers the Rule
    wrapper; this pins the pure check on the live universe)."""
    import os

    from nmfx.analysis.rules_obs import (_documented_metrics,
                                         _live_metrics,
                                         check_metric_naming)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = _documented_metrics(
        os.path.join(repo, "docs", "observability.md"))
    assert check_metric_naming(_live_metrics(), doc) == []


def test_nmfx010_bad_name_fires():
    from nmfx.analysis.rules_obs import check_metric_naming

    u = _metric_universe()
    u["live"] = dict(u["live"], nmfx_Weird="gauge")
    u["documented"] = u["documented"] | {"nmfx_Weird"}
    problems = check_metric_naming(**u)
    assert len(problems) == 1
    assert "naming scheme" in problems[0]
    assert "nmfx_Weird" in problems[0]


def test_nmfx010_counter_suffix_fires_both_ways():
    from nmfx.analysis.rules_obs import check_metric_naming

    u = _metric_universe()
    u["live"] = dict(u["live"])
    u["live"]["nmfx_serve_dispatches_total"] = "gauge"  # fake counter
    u["live"]["nmfx_ckpt_chunks_solved"] = "counter"    # missing _total
    u["documented"] = u["documented"] | {"nmfx_ckpt_chunks_solved"}
    problems = check_metric_naming(**u)
    assert len(problems) == 2
    assert any("_total" in p and "gauge" in p for p in problems)
    assert any("must end in '_total'" in p for p in problems)


def test_nmfx010_undocumented_and_stale_rows_fire():
    from nmfx.analysis.rules_obs import check_metric_naming

    u = _metric_universe(documented=frozenset(
        {"nmfx_serve_dispatches_total",
         "nmfx_serve_queue_wait_seconds",
         "nmfx_ghost_metric_total"}))
    problems = check_metric_naming(**u)
    assert len(problems) == 2
    assert any("missing from the docs" in p
               and "nmfx_serve_queue_depth" in p for p in problems)
    assert any("stale" in p and "nmfx_ghost_metric_total" in p
               for p in problems)


def test_nmfx010_rule_fires_through_run_on_mutated_docs(tmp_path,
                                                        monkeypatch):
    """End-to-end through the Rule wrapper: point the docs table at a
    copy missing one live metric's row and the registered rule goes
    red at the registry module; the real docs keep it quiet."""
    import os

    from nmfx.analysis import rules_obs

    target = ["nmfx/obs/metrics.py"]
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX010"])
                if f.rule_id == "NMFX010"]
    assert findings == []  # live tree compliant
    real = rules_obs._documented_metrics(
        os.path.join("docs", "observability.md"))
    monkeypatch.setattr(
        rules_obs, "_documented_metrics",
        lambda path: frozenset(real - {"nmfx_serve_queue_depth"}))
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX010"])
                if f.rule_id == "NMFX010"]
    assert len(findings) == 1
    assert "nmfx_serve_queue_depth" in findings[0].message
    assert findings[0].file.endswith("nmfx/obs/metrics.py")


def test_nmfx010_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX010" in RULES


# ---------------------------------------------------------------- NMFX011
# result-cache key coverage (ISSUE 16): every result-affecting
# SolverConfig/ConsensusConfig field must reach the content-addressed
# result key or be explicitly declared exempt — the stale-SERVE class
# (one finished result replayed to two configurations that must
# differ). Same pure-check + bad-universe/clean-twin + live-tree +
# mutation-through-run shape as NMFX001/NMFX007/NMFX008; the baseline
# stays empty.

def _rescache_universe(**over):
    """A minimal healthy result-cache-key universe; overrides inject
    the defect (the NMFX007 bad-universe pattern)."""
    base = dict(
        solver_fields=frozenset({"algorithm", "tol_x", "restart_chunk"}),
        consensus_fields=frozenset({"restarts", "seed", "ks",
                                    "linkage"}),
        cache_solver=frozenset({"algorithm", "tol_x"}),
        cache_consensus=frozenset({"restarts", "seed", "ks",
                                   "linkage"}),
        declared_non_numerics=("restart_chunk",),
        declared_result_cache_exempt=(),
    )
    base.update(over)
    return base


def test_nmfx011_clean_universe_quiet():
    from nmfx.analysis.rules_config import check_result_cache_coverage

    assert check_result_cache_coverage(**_rescache_universe()) == []


def test_nmfx011_live_tree_clean():
    """The shipped tree must satisfy its own key-coverage contract —
    in particular RESULT_CACHE_EXEMPT_FIELDS stays EMPTY (unlike the
    checkpoint ledger, the result cache must key restarts/ks: a
    finished restarts=4 answer is not a restarts=8 answer)."""
    from nmfx.analysis.rules_config import (
        _live_result_cache_universe, check_result_cache_coverage)

    live = _live_result_cache_universe()
    assert live["declared_result_cache_exempt"] == ()
    assert {"restarts", "ks", "seed"} <= live["cache_consensus"]
    assert check_result_cache_coverage(**live) == []


def test_nmfx011_solver_field_dropped_fires():
    from nmfx.analysis.rules_config import check_result_cache_coverage

    problems = check_result_cache_coverage(**_rescache_universe(
        cache_solver=frozenset({"algorithm"})))
    assert any("SolverConfig.tol_x" in p and "result-cache" in p
               for p in problems)


def test_nmfx011_consensus_field_dropped_fires():
    """The headline hazard: restarts invisible to the key would replay
    a narrow-budget consensus to a widened-budget request forever."""
    from nmfx.analysis.rules_config import check_result_cache_coverage

    problems = check_result_cache_coverage(**_rescache_universe(
        cache_consensus=frozenset({"seed", "ks", "linkage"})))
    assert any("ConsensusConfig.restarts" in p
               and "RESULT_CACHE_EXEMPT_FIELDS" in p for p in problems)


def test_nmfx011_declared_exemption_quiet():
    """An exclusion WITH its declaration on record is accepted — the
    rule enforces honesty, not a fixed key shape."""
    from nmfx.analysis.rules_config import check_result_cache_coverage

    assert check_result_cache_coverage(**_rescache_universe(
        cache_consensus=frozenset({"restarts", "seed", "ks"}),
        declared_result_cache_exempt=("linkage",))) == []


def test_nmfx011_stale_exempt_declaration_fires():
    from nmfx.analysis.rules_config import check_result_cache_coverage

    problems = check_result_cache_coverage(**_rescache_universe(
        declared_result_cache_exempt=("not_a_field",)))
    assert any("not_a_field" in p and "stale" in p for p in problems)


def test_nmfx011_contradictory_declaration_fires():
    """Exempt AND covered at once: one declaration is stale."""
    from nmfx.analysis.rules_config import check_result_cache_coverage

    problems = check_result_cache_coverage(**_rescache_universe(
        declared_result_cache_exempt=("linkage",)))
    assert any("linkage" in p and "contradictory" in p
               for p in problems)


def test_nmfx011_rule_fires_through_run_on_mutated_key(monkeypatch):
    """Acceptance mutation: drop 'restarts' from the live key coverage
    (without declaring it exempt) and the REGISTERED rule — through the
    real run() path over the real config.py — goes red at the
    ConsensusConfig declaration; restore and the run is quiet again."""
    from nmfx import result_cache
    from nmfx.analysis import run

    target = ["nmfx/config.py"]
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX011"])
                if f.rule_id == "NMFX011"]
    assert findings == []  # live tree compliant
    real = result_cache.cache_key_fields()
    monkeypatch.setattr(
        result_cache, "cache_key_fields",
        lambda: {"solver": real["solver"],
                 "consensus": real["consensus"] - {"restarts"}})
    findings = [f for f in run(target, jaxpr=False,
                               rule_ids=["NMFX011"])
                if f.rule_id == "NMFX011"]
    assert len(findings) == 1
    assert "ConsensusConfig.restarts" in findings[0].message
    assert findings[0].file.endswith("nmfx/config.py")
    monkeypatch.undo()
    assert [f for f in run(target, jaxpr=False, rule_ids=["NMFX011"])
            if f.rule_id == "NMFX011"] == []


def test_nmfx011_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX011" in RULES


# ---------------------------------------------------------------- NMFX012

_GUARDED_HEADER = """
    import threading
    from nmfx.guards import guarded_by

"""

_GUARDED_CLEAN = _GUARDED_HEADER + """
    @guarded_by("_lock", "_items", "count")
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self.count = 0

        def push(self, x):
            with self._lock:
                self._items.append(x)
                self.count += 1

        def flush(self):
            with self._lock:
                self._drain()

        def _drain(self):
            # no with: provably called under the lock (fixpoint)
            self._items.clear()
            self.count = 0
"""

_GUARDED_BAD = _GUARDED_HEADER + """
    @guarded_by("_lock", "_items", "count")
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self.count = 0

        def push(self, x):
            self._items.append(x)
            self.count += 1
"""


def test_nmfx012_clean_twin_quiet(tmp_path):
    """Guarded accesses under the lock — including through a private
    helper only ever called with the lock held — are clean."""
    assert _ids(_lint(tmp_path, _GUARDED_CLEAN, ("NMFX012",))) == []


def test_nmfx012_unguarded_access_fires(tmp_path):
    findings = active(_lint(tmp_path, _GUARDED_BAD, ("NMFX012",)))
    assert [f.rule_id for f in findings] == ["NMFX012", "NMFX012"]
    assert "self._items" in findings[0].message
    assert "without it in Box.push" in findings[0].message


def test_nmfx012_init_exempt(tmp_path):
    """__init__ publishes the object (happens-before); bare stores
    there are not findings — the clean twin's __init__ already passes,
    and an __init__-only class stays quiet."""
    src = _GUARDED_HEADER + """
    @guarded_by("_lock", "_items")
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
    """
    assert _ids(_lint(tmp_path, src, ("NMFX012",))) == []


def test_nmfx012_stale_declaration_fires(tmp_path):
    """Declaring a guard lock the class never creates is itself a
    finding — a silently dead declaration checks nothing."""
    src = _GUARDED_HEADER + """
    @guarded_by("_missing_lock", "_items")
    class Box:
        def __init__(self):
            self._items = []
    """
    findings = active(_lint(tmp_path, src, ("NMFX012",)))
    assert len(findings) == 1
    assert "_missing_lock" in findings[0].message


def test_nmfx012_suppression_with_reason(tmp_path):
    """The standard machinery applies: an inline reasoned suppression
    silences one access (single-thread confinement the analysis cannot
    see), and active() goes green."""
    src = _GUARDED_HEADER + """
    @guarded_by("_lock", "count")
    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def peek(self):
            return self.count  # nmfx: ignore[NMFX012] -- racy read OK
    """
    findings = _lint(tmp_path, src, ("NMFX012",))
    assert _ids(findings) == []
    assert any(f.suppressed for f in findings)


def test_nmfx012_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX012" in RULES


# ---------------------------------------------------------------- NMFX013

def test_nmfx013_clean_consistent_order_quiet(tmp_path):
    src = """
    import threading

    class Svc:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._a:
                with self._b:
                    pass
    """
    assert _ids(_lint(tmp_path, src, ("NMFX013",))) == []


def test_nmfx013_inverted_order_cycle_fires(tmp_path):
    """The PR-7 deadlock shape: the resolver path nests lock -> tracked
    while the expiry path nests tracked -> lock (via a helper the call
    graph resolves) — a cycle, i.e. two threads can deadlock."""
    src = """
    import threading

    class Svc:
        def __init__(self):
            self._lock = threading.Lock()
            self._tracked_lock = threading.Lock()

        def resolve(self):
            with self._lock:
                self._untrack()

        def _untrack(self):
            with self._tracked_lock:
                pass

        def expire(self):
            with self._tracked_lock:
                with self._lock:
                    pass
    """
    findings = active(_lint(tmp_path, src, ("NMFX013",)))
    assert len(findings) == 1
    assert "cycle" in findings[0].message
    assert "_lock" in findings[0].message


def test_nmfx013_plain_lock_reentry_fires(tmp_path):
    """A plain Lock re-acquired through a self-call is a guaranteed
    self-deadlock (the PR-10 SIGTERM incident shape)."""
    src = """
    import threading

    class Rec:
        def __init__(self):
            self._lock = threading.Lock()

        def dump(self):
            with self._lock:
                self.snapshot()

        def snapshot(self):
            with self._lock:
                return 1
    """
    findings = active(_lint(tmp_path, src, ("NMFX013",)))
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_nmfx013_rlock_reentry_exempt(tmp_path):
    """The same shape on an RLock is the documented fix — quiet."""
    src = """
    import threading

    class Rec:
        def __init__(self):
            self._lock = threading.RLock()

        def dump(self):
            with self._lock:
                self.snapshot()

        def snapshot(self):
            with self._lock:
                return 1
    """
    assert _ids(_lint(tmp_path, src, ("NMFX013",))) == []


def test_nmfx013_live_tree_acyclic():
    """The real service tier's static lock graph has no cycles — the
    deadlock-freedom contract docs/serving.md documents."""
    findings = [f for f in run(["nmfx"], jaxpr=False,
                               rule_ids=["NMFX013"])
                if f.rule_id == "NMFX013"]
    assert findings == []


def test_nmfx013_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX013" in RULES


# ---------------------------------------------------------------- NMFX014

def test_nmfx014_dead_future_fires(tmp_path):
    src = """
    from concurrent.futures import Future

    class Svc:
        def submit(self, k):
            fut = Future()
            return k
    """
    findings = active(_lint(tmp_path, src, ("NMFX014",)))
    assert len(findings) == 1
    assert "never resolves" in findings[0].message


def test_nmfx014_unprotected_publication_gap_fires(tmp_path):
    """The harvest-submit shape this PR fixed: publish into a pending
    map, then a failable call with no handler that resolves or
    unpublishes — the waiter strands."""
    src = """
    from concurrent.futures import Future

    class Pipe:
        def submit(self, k):
            fut = Future()
            self._futures[k] = fut
            self._spawn_worker()

        def _spawn_worker(self):
            raise RuntimeError
    """
    findings = active(_lint(tmp_path, src, ("NMFX014",)))
    assert len(findings) == 1
    assert "publishes Future" in findings[0].message
    assert "_spawn_worker" in findings[0].message


def test_nmfx014_protecting_handler_quiet(tmp_path):
    """The replica-forward shape: the risky hand-off sits under a
    handler that unpublishes and re-raises — clean."""
    src = """
    from concurrent.futures import Future

    class Rep:
        def forward(self, rid):
            fut = Future()
            self._pending[rid] = fut
            try:
                self._write_record(rid)
            except Exception:
                self._pending.pop(rid, None)
                raise
            return fut

        def _write_record(self, rid):
            raise OSError
    """
    assert _ids(_lint(tmp_path, src, ("NMFX014",))) == []


def test_nmfx014_lexical_resolution_quiet(tmp_path):
    """The exec-cache shape: the function itself resolves the future
    after the work — the publication gap is the producer's own body,
    already covered by its try/except discipline (NMFX006)."""
    src = """
    from concurrent.futures import Future

    class Cache:
        def executable(self, key):
            fut = Future()
            self._inflight[key] = fut
            entry = self._build(key)
            fut.set_result(entry)
            return entry

        def _build(self, key):
            return key
    """
    assert _ids(_lint(tmp_path, src, ("NMFX014",))) == []


def test_nmfx014_ownership_transfer_quiet(tmp_path):
    """Passing the future to another owner (wrapper dataclass, another
    component's register call) transfers the resolution obligation."""
    src = """
    from concurrent.futures import Future

    def dispatch(router, req):
        fut = Future()
        router.register(req, fut)
    """
    assert _ids(_lint(tmp_path, src, ("NMFX014",))) == []


def test_nmfx014_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX014" in RULES


# ---------------------------------------------------------------- NMFX015

def test_nmfx015_unowned_thread_fires(tmp_path):
    src = """
    import threading

    class Svc:
        def start(self):
            t = threading.Thread(target=self._run)
            t.start()
    """
    findings = active(_lint(tmp_path, src, ("NMFX015",)))
    assert len(findings) == 1
    assert "non-daemon" in findings[0].message


def test_nmfx015_daemon_quiet(tmp_path):
    src = """
    import threading

    class Svc:
        def start(self):
            t = threading.Thread(target=self._run, daemon=True)
            t.start()
    """
    assert _ids(_lint(tmp_path, src, ("NMFX015",))) == []


def test_nmfx015_joined_container_quiet(tmp_path):
    """Threads stored in a container the owner drains with join() on
    its close path are owned lifetimes — quiet."""
    src = """
    import threading

    class Svc:
        def start(self):
            t = threading.Thread(target=self._run)
            t.start()
            self._threads.append(t)

        def close(self):
            for t in self._threads:
                t.join()
    """
    assert _ids(_lint(tmp_path, src, ("NMFX015",))) == []


def test_nmfx015_local_join_quiet(tmp_path):
    """A run-and-wait helper joins its thread locally — quiet."""
    src = """
    import threading

    def run_both(fn):
        t = threading.Thread(target=fn)
        t.start()
        fn()
        t.join()
    """
    assert _ids(_lint(tmp_path, src, ("NMFX015",))) == []


def test_nmfx015_timer_cancel_quiet(tmp_path):
    """Timers cancelled on the owner's close path count as joined."""
    src = """
    import threading

    class Svc:
        def start(self):
            self._timer = threading.Timer(5.0, self._fire)
            self._timer.start()

        def close(self):
            self._timer.cancel()
    """
    assert _ids(_lint(tmp_path, src, ("NMFX015",))) == []


def test_nmfx015_rule_registered():
    from nmfx.analysis import RULES

    assert "NMFX015" in RULES
