"""nmfx/agreement.py — the sketched engine's consensus-level accuracy
yardstick (ISSUE 12): ARI and pairwise co-membership agreement pinned
against hand-computed small cases, permutation invariance, and the
degenerate single-cluster conventions."""

import numpy as np
import pytest

from nmfx.agreement import (adjusted_rand_index, consensus_agreement,
                            cophenetic_gap, membership_agreement)


# -- membership (pairwise) agreement: hand-computed ---------------------
def test_pair_agreement_identical():
    assert membership_agreement([1, 1, 2, 2], [1, 1, 2, 2]) == 1.0


def test_pair_agreement_relabeled_is_identical():
    # co-membership structure only — label VALUES must not matter
    assert membership_agreement([1, 1, 2, 2], [7, 7, 3, 3]) == 1.0


def test_pair_agreement_hand_computed():
    # a=[1,1,2,2], b=[1,2,2,2]: pairs (6 total):
    # (0,1): a together, b apart  -> disagree
    # (0,2): apart, apart         -> agree
    # (0,3): apart, apart         -> agree
    # (1,2): apart, together      -> disagree
    # (1,3): apart, together      -> disagree
    # (2,3): together, together   -> agree
    assert membership_agreement([1, 1, 2, 2],
                                [1, 2, 2, 2]) == pytest.approx(3 / 6)


def test_pair_agreement_single_sample_vacuous():
    assert membership_agreement([1], [2]) == 1.0


def test_pair_agreement_length_mismatch():
    with pytest.raises(ValueError, match="equal length"):
        membership_agreement([1, 2], [1, 2, 3])


# -- adjusted Rand index: hand-computed ---------------------------------
def test_ari_identical_partition():
    assert adjusted_rand_index([1, 1, 2, 2], [1, 1, 2, 2]) == 1.0


def test_ari_permutation_invariance():
    a = [0, 0, 1, 1, 2, 2]
    for perm in (
            [2, 2, 0, 0, 1, 1],
            [5, 5, 9, 9, 1, 1],
    ):
        assert adjusted_rand_index(a, perm) == 1.0
        assert membership_agreement(a, perm) == 1.0


def test_ari_hand_computed():
    """a=[1,1,1,2,2,2], b=[1,1,2,2,2,2]: contingency [[2,1],[0,3]].
    sum_idx = C(2,2)+C(1,2)+C(3,2) = 1+0+3 = 4; sum_a = 2*C(3,2) = 6;
    sum_b = C(2,2)+C(4,2) = 1+6 = 7; total = C(6,2) = 15;
    expected = 6*7/15 = 2.8; max = 6.5;
    ARI = (4-2.8)/(6.5-2.8) = 1.2/3.7."""
    got = adjusted_rand_index([1, 1, 1, 2, 2, 2], [1, 1, 2, 2, 2, 2])
    assert got == pytest.approx(1.2 / 3.7)


def test_ari_symmetry():
    a = [1, 1, 1, 2, 2, 2]
    b = [1, 1, 2, 2, 2, 2]
    assert adjusted_rand_index(a, b) == pytest.approx(
        adjusted_rand_index(b, a))


def test_ari_opposed_partitions_nonpositive():
    # maximally crossed 2x2 design: each cluster of a splits evenly
    # over b's clusters — chance-level agreement, ARI ~ 0 (<= 0 here)
    a = [1, 1, 2, 2]
    b = [1, 2, 1, 2]
    assert adjusted_rand_index(a, b) <= 0.0


# -- degenerate partitions ----------------------------------------------
def test_ari_both_single_cluster():
    assert adjusted_rand_index([3, 3, 3], [8, 8, 8]) == 1.0


def test_ari_both_all_singletons():
    assert adjusted_rand_index([1, 2, 3], [5, 6, 7]) == 1.0


def test_ari_single_cluster_vs_singletons():
    # "no structure" in two INCOMPATIBLE senses: zero agreement
    assert adjusted_rand_index([1, 1, 1], [1, 2, 3]) == 0.0


def test_empty_labelings_rejected():
    with pytest.raises(ValueError, match="non-empty"):
        adjusted_rand_index([], [])


# -- result-level report ------------------------------------------------
class _FakeK:
    def __init__(self, membership, rho):
        self.membership = np.asarray(membership)
        self.rho = rho


class _FakeResult:
    def __init__(self, per_k):
        self.per_k = per_k
        self.ks = tuple(per_k)


def test_consensus_agreement_report():
    ra = _FakeResult({2: _FakeK([1, 1, 2, 2], 0.99),
                      3: _FakeK([1, 2, 3, 3], 0.90)})
    rb = _FakeResult({2: _FakeK([2, 2, 1, 1], 1.00),
                      3: _FakeK([1, 2, 3, 3], 0.80)})
    rep = consensus_agreement(ra, rb)
    assert rep["per_k"][2]["ari"] == 1.0
    assert rep["per_k"][3]["ari"] == 1.0
    assert rep["min_ari"] == 1.0
    assert rep["max_rho_gap"] == pytest.approx(0.10)
    assert cophenetic_gap(ra, rb) == pytest.approx(0.10)


def test_consensus_agreement_rejects_disjoint_ranks():
    ra = _FakeResult({2: _FakeK([1, 1], 1.0)})
    rb = _FakeResult({3: _FakeK([1, 1], 1.0)})
    with pytest.raises(ValueError, match="share no ranks"):
        consensus_agreement(ra, rb)
    with pytest.raises(ValueError, match="not present in both"):
        consensus_agreement(ra, ra, ks=(5,))
