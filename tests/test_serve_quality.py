"""Quality-elastic serving (``ServeConfig.quality_elastic`` — ISSUE 12):
the scheduler degrades deadline-pressured and admission-shed requests to
the sketched engine instead of expiring/rejecting them, and the result
is ALWAYS typed and tagged — ``ConsensusResult.quality``,
``RequestStats.quality``/``degraded_cause``, the
``nmfx_serve_quality_degraded_total{cause=…}`` counter, and a
``serve.quality_degraded`` flight event. The lint fixture at the bottom
pins the structural half: no ``ConsensusResult`` construction in
``nmfx/serve.py`` may omit the quality tag (the NMFX006-style
machine-checked invariant the ISSUE asks for)."""

import ast
import inspect
import time

import numpy as np
import pytest

import nmfx.serve as serve_mod
from nmfx.config import SolverConfig
from nmfx.datasets import two_group_matrix
from nmfx.obs import flight, metrics
from nmfx.serve import NMFXServer, QueueFull, ServeConfig


@pytest.fixture(scope="module")
def matrix():
    return two_group_matrix(n_genes=60, n_per_group=8, seed=1)


SCFG = SolverConfig(algorithm="mu", max_iter=150)


def _degraded_metric(cause):
    c = metrics.registry().get("nmfx_serve_quality_degraded_total")
    return 0.0 if c is None else c.value(cause=cause)


# -- deadline degradation -----------------------------------------------
def test_deadline_pressure_degrades_tagged(matrix):
    before = _degraded_metric("deadline")
    cfg = ServeConfig(quality_elastic=True, iter_rate_estimate=1.0)
    with NMFXServer(cfg) as srv:
        # remaining budget ~60 iters << max_iter: without elasticity
        # this request would dispatch CLAMPED; with it, it dispatches
        # sketched at the full budget
        fut = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG,
                         timeout=60)
        res = fut.result(timeout=300)
    assert res.quality == "sketched"
    assert fut.stats.quality == "sketched"
    assert fut.stats.degraded_cause == "deadline"
    assert fut.stats.budget_iters is None  # degraded, not clamped
    assert srv.stats()["quality_degraded"] == 1
    assert _degraded_metric("deadline") == before + 1
    events = flight.default_recorder().events("serve.quality_degraded")
    assert any(e.get("cause") == "deadline" for e in events)


def test_deadline_without_elastic_still_clamps(matrix):
    cfg = ServeConfig(iter_rate_estimate=1.0)
    with NMFXServer(cfg) as srv:
        fut = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG,
                         timeout=60)
        res = fut.result(timeout=300)
    assert res.quality == "exact"
    assert fut.stats.degraded_cause is None
    assert fut.stats.budget_iters is not None  # the pre-existing clamp


def test_ineligible_algorithm_not_degraded(matrix):
    # als has no sketched form: the deadline clamp applies as before
    cfg = ServeConfig(quality_elastic=True, iter_rate_estimate=1.0)
    with NMFXServer(cfg) as srv:
        fut = srv.submit(matrix, ks=(2,), restarts=3,
                         solver_cfg=SolverConfig(algorithm="als",
                                                 max_iter=150),
                         timeout=60)
        res = fut.result(timeout=300)
    assert res.quality == "exact"
    assert fut.stats.degraded_cause is None


# -- overload degradation -----------------------------------------------
def test_overload_soft_admission_degrades_tagged(matrix):
    before = _degraded_metric("overload")
    cfg = ServeConfig(quality_elastic=True, max_queue_depth=1)
    with NMFXServer(cfg, start=False) as srv:  # paused: deterministic
        f1 = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG)
        # over the depth bound: soft-admitted degraded, not rejected
        f2 = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG)
        # the 2x hard bound still sheds
        with pytest.raises(QueueFull):
            srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG)
        srv.resume()
        r1 = f1.result(timeout=300)
        r2 = f2.result(timeout=300)
    assert r1.quality == "exact"
    assert r2.quality == "sketched"
    assert f2.stats.degraded_cause == "overload"
    assert f2.stats.quality == "sketched"
    assert srv.stats()["quality_degraded"] == 1
    assert _degraded_metric("overload") == before + 1


def test_overload_without_elastic_rejects(matrix):
    cfg = ServeConfig(max_queue_depth=1)
    with NMFXServer(cfg, start=False) as srv:
        f1 = srv.submit(matrix, ks=(2,), restarts=3, solver_cfg=SCFG)
        with pytest.raises(QueueFull):
            srv.submit(matrix, ks=(2,), restarts=3, solver_cfg=SCFG)
        srv.resume()
        f1.result(timeout=300)


def test_pending_bytes_bound_stays_hard(matrix):
    cfg = ServeConfig(quality_elastic=True, max_queue_depth=8,
                      max_pending_bytes=matrix.nbytes + 1)
    with NMFXServer(cfg, start=False) as srv:
        f1 = srv.submit(matrix, ks=(2,), restarts=3, solver_cfg=SCFG)
        with pytest.raises(QueueFull, match="bytes"):
            srv.submit(matrix, ks=(2,), restarts=3, solver_cfg=SCFG)
        srv.resume()
        f1.result(timeout=300)


def test_degraded_request_never_packs(matrix):
    """A degraded request must dispatch SOLO: its lanes run a different
    engine than exact dispatch-mates would."""
    cfg = ServeConfig(quality_elastic=True, max_queue_depth=1)
    with NMFXServer(cfg, start=False) as srv:
        f1 = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG)
        f2 = srv.submit(matrix, ks=(2,), restarts=4, solver_cfg=SCFG)
        srv.resume()
        r1, r2 = f1.result(timeout=300), f2.result(timeout=300)
    assert f2.stats.packed_requests == 1  # solo by construction
    assert r2.quality == "sketched"
    assert r1.quality == "exact"


# -- native sketched requests -------------------------------------------
def test_native_sketched_request_tagged_not_degraded(matrix):
    with NMFXServer(ServeConfig()) as srv:
        fut = srv.submit(matrix, ks=(2,), restarts=4,
                         solver_cfg=SolverConfig(algorithm="mu",
                                                 max_iter=150,
                                                 backend="sketched"))
        res = fut.result(timeout=300)
    assert res.quality == "sketched"
    assert fut.stats.quality == "sketched"
    assert fut.stats.degraded_cause is None
    assert srv.stats()["quality_degraded"] == 0


# -- config/key coverage ------------------------------------------------
def test_quality_elastic_in_serve_key_fields():
    from nmfx.serve import serve_key_fields

    assert "quality_elastic" in serve_key_fields()


# -- the lint fixture (NMFX006-style machine check) ---------------------
def test_every_serve_consensusresult_sets_quality():
    """Structural gate: EVERY ``ConsensusResult(...)`` construction in
    nmfx/serve.py must pass an explicit ``quality=`` keyword — the
    "no path may return an untagged sketched result to a caller who
    requested exact" invariant, checked against the source so a new
    construction site cannot ship untagged."""
    src = inspect.getsource(serve_mod)
    tree = ast.parse(src)
    sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name == "ConsensusResult":
                sites.append(node)
    assert sites, "expected at least one ConsensusResult site in serve"
    for node in sites:
        kwargs = {kw.arg for kw in node.keywords}
        assert "quality" in kwargs, (
            f"nmfx/serve.py line {node.lineno}: ConsensusResult "
            "constructed without quality= — a sketched-served request "
            "could reach its caller untagged")


def test_degradation_requires_opt_in(matrix):
    """quality_elastic defaults OFF: no degradation machinery fires on
    a default server (the flag is load-bearing for the contract)."""
    assert ServeConfig().quality_elastic is False
