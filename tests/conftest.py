"""Test harness: force an 8-device virtual CPU platform.

Mirrors SURVEY.md §4's plan — the mesh/sharding code paths are exercised
without TPUs via 8 virtual CPU devices (the reference has no test suite at
all; this pyramid replaces its run-and-eyeball smoke script, reference
``test_nmf.r:25-27``).

Note: env vars (JAX_PLATFORMS/XLA_FLAGS) are NOT enough here — a
sitecustomize may import jax and register a TPU plugin before pytest starts.
Backend *initialization* is lazy, so jax.config updates at conftest import
time still win, as long as no test module touches devices at import time.
"""

import jax

from nmfx._compat import force_cpu_devices

force_cpu_devices(8)

import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _xdist_worker_compile_cache(tmp_path_factory):
    """Under pytest-xdist, give EACH worker process its own persistent
    XLA compile cache. The round-5 incident class — a cache entry
    half-written by one process segfaulting a concurrent reader inside
    jax's cache deserialization — was a SHARED-directory problem;
    per-worker directories keep the compile amortization (workers re-use
    their own entries across modules) with no cross-process readers by
    construction. No-op outside xdist (PYTEST_XDIST_WORKER unset): the
    single-process tier-1 run stays uncached, exactly as before."""
    import os

    worker = os.environ.get("PYTEST_XDIST_WORKER")
    if worker is None:
        yield
        return
    cache_dir = str(tmp_path_factory.mktemp(f"xla_cache_{worker}"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    yield


@pytest.fixture(scope="session", autouse=True)
def _isolate_compile_cache(tmp_path_factory):
    """Point the CLI's default-on persistent compile cache at a
    per-SESSION tmp dir. Without this, tests that invoke
    ``nmfx.cli.main`` share the USER's ~/.cache/nmfx/xla — and a cache
    entry half-written by a concurrent real-TPU bench in another
    process segfaults the reader inside jax's cache deserialization
    (observed round 5: the full suite died at a cache read while TPU
    probes were running). Session scope keeps intra-run compile reuse
    between CLI tests while isolating them from other processes."""
    from nmfx import cli

    old = cli._DEFAULT_COMPILE_CACHE
    cli._DEFAULT_COMPILE_CACHE = str(tmp_path_factory.mktemp("xla_cache"))
    yield
    cli._DEFAULT_COMPILE_CACHE = old


@pytest.fixture(scope="module", autouse=True)
def _clear_jit_caches_between_modules():
    """Drop compiled executables between test modules. A full
    single-process suite accumulates many hundreds of CPU executables
    and the XLA CPU compiler was observed to SEGFAULT deep into the
    suite (reproducibly at the shapes-fuzz module, in
    backend_compile_and_load — an upstream accumulation bug, not a test
    bug: the same module passes standalone). Clearing per module keeps
    the per-process executable population bounded at the cost of some
    recompilation."""
    yield
    jax.clear_caches()


@pytest.fixture(autouse=True)
def _restore_compile_cache_config():
    """``cli.main`` enables the persistent compile cache via a GLOBAL
    ``jax.config`` update, which would otherwise stay active for every
    test after the CLI tests — routing all later compiles through
    jax's cache writer, which segfaults deterministically on this
    platform partway through the suite (reproduced 3× at
    test_solver_shapes_fuzz, stack in compilation_cache.put/get).
    Restore the setting after each test so only the CLI tests
    themselves run cached."""
    old = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    # restore UNCONDITIONALLY: a test that restores the dir itself
    # would otherwise skip the reset below and leave jax's memoized
    # cache object (and is_cache_used latch) alive; cli.main also
    # lowers the min-compile-time threshold globally
    jax.config.update("jax_compilation_cache_dir", old)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      old_min)
    # the config alone is not enough: jax initializes its cache object
    # at most once per process and keeps using it after the config
    # reverts — drop it so post-CLI tests really compile uncached
    from jax._src import compilation_cache

    compilation_cache.reset_cache()


#: test modules that run threads (serve scheduler/watchdog/harvesters,
#: streamed harvest workers, chaos injection) — the suites a
#: lost-wakeup or deadlock regression would otherwise turn into a
#: silent multi-minute hang
_THREADED_MODULES = frozenset({
    "test_serve", "test_harvest", "test_faults", "test_pipeline"})

#: per-test hang budget for those modules, seconds. Generous against
#: the slowest legitimate test (cold compiles on this CPU image are
#: tens of seconds) but a small fraction of the 870 s tier-1 budget: a
#: watchdog/drain regression fails ONE test in 4 minutes with a full
#: thread dump instead of eating the whole run. Override via
#: NMFX_TEST_HANG_GUARD_S (0 disables — debugger sessions).
_HANG_GUARD_S = 240.0


@pytest.fixture(autouse=True)
def _threaded_hang_guard(request):
    """Per-test hang guard for the threaded suites (ISSUE 7 satellite):
    ``faulthandler.dump_traceback_later`` dumps EVERY thread's stack and
    kills the process when a test overstays ``_HANG_GUARD_S`` — turning
    a hung Future (the exact failure class the serve watchdog exists to
    prevent) into a loud, attributed tier-1 failure with the stuck
    stacks in the log."""
    import faulthandler
    import os

    mod = request.node.fspath.purebasename \
        if request.node.fspath else ""
    if mod not in _THREADED_MODULES:
        yield
        return
    budget = float(os.environ.get("NMFX_TEST_HANG_GUARD_S",
                                  _HANG_GUARD_S))
    if budget <= 0:
        yield
        return
    # flight-recorder postmortem BEFORE the kill (ISSUE 10): the
    # faulthandler exit below is C-level — no Python runs after it —
    # so a timer slightly inside the budget writes the structured
    # event ring (dispatches, degradations, fault fires, watchdog
    # actions leading up to the hang) next to the thread dump. Armed
    # only for the threaded suites this guard already covers.
    import threading

    from nmfx.obs import flight

    safe = "".join(c if c.isalnum() or c in "-._" else "-"
                   for c in request.node.name)[:60]
    dump_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"),
        f"nmfx_flight_hang_{os.getpid()}_{safe}.json")
    timer = threading.Timer(
        max(budget - 5.0, budget * 0.5),
        lambda: flight.dump(f"test-hang-guard:{request.node.name}",
                            path=dump_path))
    timer.daemon = True
    timer.start()
    faulthandler.dump_traceback_later(budget, exit=True)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()
        timer.cancel()
        try:
            # a slow-but-passing test that tripped the timer must not
            # leave a false hang postmortem; a genuine hang never
            # reaches this finally (faulthandler killed the process)
            os.unlink(dump_path)
        except OSError:
            pass


#: suites where the runtime lock-order witness is armed: every module
#: that drives the service tier's threads (scheduler/harvesters,
#:  router maintenance, replica heartbeats, chaos) plus the coalescing
#: and serve-quality suites that exercise the done-callback paths.
#: Disable with NMFX_LOCK_WITNESS=0 (e.g. when bisecting a timing
#: issue the instrumentation could perturb).
_WITNESS_MODULES = frozenset({
    "test_serve", "test_serve_quality", "test_harvest", "test_faults",
    "test_pipeline", "test_router", "test_fleet", "test_coalesce"})


@pytest.fixture(autouse=True)
def _lock_order_witness(request):
    """Arm the instrumented-lock witness (nmfx.analysis.witness) for
    the threaded suites: locks the test creates record their real
    acquisition orders, and the teardown fails the test on any dynamic
    inversion (two creation sites acquired in both orders — the
    precondition of every real deadlock) or any order contradicting
    the static NMFX013 graph. docs/analysis.md "Runtime witness"."""
    import os

    mod = request.node.fspath.purebasename \
        if request.node.fspath else ""
    if (mod not in _WITNESS_MODULES
            or os.environ.get("NMFX_LOCK_WITNESS", "1") == "0"):
        yield
        return
    from nmfx.analysis import witness

    witness.reset()
    witness.arm()
    try:
        yield
    finally:
        witness.disarm()
        problems = witness.violations() + witness.check_static_inversions()
        witness.reset()
    assert not problems, (
        "lock-order witness caught an inversion:\n"
        + witness.render(problems))


@pytest.fixture(autouse=True)
def _tracer_state_isolated():
    """A test that enables the process-wide structured tracer
    (nmfx.obs.trace) must not leave it on for every later test — the
    enabled path records spans on each profiler phase, and span
    content from one test bleeding into another's export would make
    the trace round-trip tests order-dependent."""
    from nmfx.obs import trace

    was = trace.default_tracer().enabled
    yield
    trace.default_tracer().enabled = was


@pytest.fixture(scope="session")
def two_group_data():
    """Synthetic 2-group expression-like matrix (fixture factory standing in
    for the reference's OCplus MAsim.smyth generator, test_nmf.r:1-3, and its
    bundled 20+20x1000.gct two-group design)."""
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=120, n_per_group=12, seed=7)


@pytest.fixture(scope="session")
def low_rank_data():
    """Exactly low-rank non-negative matrix A = W H with known k."""
    rng = np.random.default_rng(0)
    w = rng.uniform(0.5, 1.5, size=(60, 3))
    h = rng.uniform(0.5, 1.5, size=(3, 25))
    return np.asarray(w @ h), 3
