"""Test harness: force an 8-device virtual CPU platform BEFORE jax import.

Mirrors SURVEY.md §4's plan — the mesh/sharding code paths are exercised
without TPUs via ``--xla_force_host_platform_device_count`` (the reference has
no test suite at all; this pyramid replaces its run-and-eyeball smoke script,
reference ``test_nmf.r:25-27``).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import pytest


@pytest.fixture(scope="session")
def two_group_data():
    """Synthetic 2-group expression-like matrix (fixture factory standing in
    for the reference's OCplus MAsim.smyth generator, test_nmf.r:1-3, and its
    bundled 20+20x1000.gct two-group design)."""
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=120, n_per_group=12, seed=7)


@pytest.fixture(scope="session")
def low_rank_data():
    """Exactly low-rank non-negative matrix A = W H with known k."""
    rng = np.random.default_rng(0)
    w = rng.uniform(0.5, 1.5, size=(60, 3))
    h = rng.uniform(0.5, 1.5, size=(3, 25))
    return np.asarray(w @ h), 3
