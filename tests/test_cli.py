"""CLI surface tests (reference entry semantics: runNMFinJobs args,
nmf.r:106) — run in-process on the 8-device virtual CPU platform."""

import os

import pytest

from nmfx.cli import main, parse_ks
from nmfx.io import write_gct


@pytest.fixture(scope="module")
def gct_path(tmp_path_factory):
    from nmfx.datasets import two_group_matrix

    a = two_group_matrix(n_genes=60, n_per_group=8, seed=1)
    path = tmp_path_factory.mktemp("cli") / "demo.gct"
    write_gct(a, str(path), row_names=[f"g{i}" for i in range(60)],
              col_names=[f"s{i}" for i in range(16)])
    return str(path)


def test_parse_ks():
    assert parse_ks("2-5") == (2, 3, 4, 5)
    assert parse_ks("2,4,8") == (2, 4, 8)
    assert parse_ks("3") == (3,)


def test_cli_smoke(gct_path, capsys):
    rc = main([gct_path, "--ks", "2-3", "--restarts", "4",
               "--maxiter", "150", "--no-files"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "best k = 2" in out


def test_cli_grid_shards(gct_path, capsys):
    rc = main([gct_path, "--ks", "2", "--restarts", "4", "--maxiter", "100",
               "--no-files", "--feature-shards", "2", "--sample-shards", "2"])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out


def test_cli_rejects_bad_combos(gct_path):
    with pytest.raises(SystemExit):
        main([gct_path, "--feature-shards", "2", "--no-mesh", "--no-files"])
    with pytest.raises(SystemExit):
        # pg has no dense-batched block — als joined PACKED_ALGORITHMS
        # in round 5, so it no longer serves as the reject case
        main([gct_path, "--backend", "packed", "--algorithm", "pg",
              "--no-files"])
    with pytest.raises(SystemExit):
        main([gct_path, "--trace-dir", "/tmp/x", "--no-files"])
    with pytest.raises(SystemExit):
        # clean usage error, not a ValueError traceback (reference guard
        # nmf.r:107-108)
        main([gct_path, "--ks", "1-3", "--no-files"])
    with pytest.raises(SystemExit):
        main([gct_path, "--backend", "pallas", "--algorithm", "hals",
              "--no-files"])


def test_cli_writes_outputs(gct_path, tmp_path, capsys):
    outdir = tmp_path / "out"
    rc = main([gct_path, "--ks", "2", "--restarts", "3", "--maxiter", "100",
               "--outdir", str(outdir), "--no-plots"])
    assert rc == 0
    names = {p.name for p in outdir.iterdir()}
    assert "cophenetic.txt" in names
    assert "consensus.k.2.gct" in names


def test_cli_shard_flag_validation(gct_path):
    for argv in (
        [gct_path, "--feature-shards", "0", "--no-files"],
        [gct_path, "--feature-shards", "16", "--no-files"],  # > devices
        [gct_path, "--feature-shards", "2", "--algorithm", "als",
         "--no-files"],
    ):
        with pytest.raises(SystemExit):
            main(argv)


def test_cli_keep_factors_saves_factors(gct_path, tmp_path, capsys):
    from nmfx.api import ConsensusResult

    out = str(tmp_path / "res.npz")
    rc = main([gct_path, "--ks", "2", "--restarts", "3", "--maxiter", "100",
               "--no-files", "--keep-factors", "--save-result", out])
    assert rc == 0
    res = ConsensusResult.load(out)
    assert res.per_k[2].all_w.shape[0] == 3
    # refused with grid shards (library contract surfaced as a usage
    # error) — pin the refusal REASON, since on this 8-device platform a
    # bare SystemExit could also come from mesh construction
    with pytest.raises(SystemExit):
        main([gct_path, "--keep-factors", "--feature-shards", "2",
              "--no-files"])
    assert "not supported with grid shards" in capsys.readouterr().err


def test_cli_compile_cache_flag(gct_path, tmp_path, capsys):
    cache = str(tmp_path / "xla-cache")
    # process-wide config is restored (and jax's memoized cache object
    # reset) by conftest's _restore_compile_cache_config fixture — an
    # in-test finally restoring the dir would defeat the fixture's
    # change detection and skip the reset
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "50", "--no-files",
               "--compile-cache", cache])
    assert rc == 0
    import os

    assert os.path.isdir(cache)  # cache directory created and used


def test_cli_kl_and_nndsvd_on_grid_shards(gct_path, capsys):
    """kl and NNDSVD compose with grid shards from the CLI (the library
    paths behind --feature-shards/--sample-shards for both)."""
    rc = main([gct_path, "--ks", "2", "--restarts", "4", "--maxiter", "100",
               "--no-files", "--algorithm", "kl", "--feature-shards", "2",
               "--sample-shards", "2"])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out
    rc = main([gct_path, "--ks", "2", "--restarts", "2", "--maxiter", "100",
               "--no-files", "--init", "nndsvd", "--feature-shards", "2"])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out


def test_grid_mesh_validation():
    from nmfx.sweep import grid_mesh

    with pytest.raises(ValueError, match="devices"):
        grid_mesh(None, 16, 1)  # f*s exceeds the 8 test devices
    with pytest.raises(ValueError, match=">= 1"):
        grid_mesh(2, 0, 1)


def test_cli_rejects_vmap_with_shards(gct_path):
    with pytest.raises(SystemExit):
        main([gct_path, "--feature-shards", "2", "--backend", "vmap",
              "--no-files"])


def test_cli_verbose_progress(gct_path, caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="nmfx"):
        rc = main([gct_path, "--ks", "2", "--restarts", "3",
                   "--maxiter", "100", "--no-files", "--verbose"])
    assert rc == 0
    assert any("k=2:" in r.message for r in caplog.records)


def test_cli_save_result(gct_path, tmp_path, capsys):
    from nmfx.api import ConsensusResult

    path = str(tmp_path / "res.npz")
    rc = main([gct_path, "--ks", "2", "--restarts", "3", "--maxiter", "100",
               "--no-files", "--save-result", path])
    assert rc == 0
    loaded = ConsensusResult.load(path)
    assert loaded.best_k == 2


def test_cli_version(capsys):
    import nmfx

    with pytest.raises(SystemExit) as e:
        main(["--version"])
    assert e.value.code == 0
    assert nmfx.__version__ in capsys.readouterr().out


def test_cli_exec_cache_and_warm_shapes(gct_path, tmp_path, capsys):
    # warmup shares the run's bucket: the sweep itself must HIT the
    # warmed executable (demo.gct is 60x16; warm a nearby shape).
    # --warm-cache backgrounds the warmup and --cache-dir persists the
    # warmed executable to disk — one run exercises all three flags.
    cache_dir = tmp_path / "exec-cache"
    rc = main([gct_path, "--ks", "2-3", "--restarts", "4",
               "--maxiter", "150", "--no-files",
               "--warm-shapes", "64x16", "--warm-cache",
               "--cache-dir", str(cache_dir)])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "in the background" in cap.err
    assert "warmed bucket" in cap.err  # report printed after the join
    # the warmed executable persisted for future processes
    assert any(name.endswith(".nmfxexec")
               for name in os.listdir(cache_dir))


def test_cli_warm_shapes_validation(gct_path):
    with pytest.raises(SystemExit):
        main([gct_path, "--warm-shapes", "60xx16", "--no-files"])
    with pytest.raises(SystemExit):
        main([gct_path, "--warm-shapes", "60x0", "--no-files"])
    with pytest.raises(SystemExit):
        # exec cache + grid shards don't compose
        main([gct_path, "--exec-cache", "--feature-shards", "2",
              "--no-files"])
    with pytest.raises(SystemExit):
        # pg can't run through the whole-grid scheduler
        main([gct_path, "--warm-shapes", "64x16", "--algorithm", "pg",
              "--no-files"])
    with pytest.raises(SystemExit):
        # --warm-cache backgrounds the --warm-shapes warmup; alone it
        # has nothing to warm
        main([gct_path, "--warm-cache", "--no-files"])


def test_cli_exec_cache_rejects_checkpoint_dir(gct_path, tmp_path):
    with pytest.raises(SystemExit):
        main([gct_path, "--exec-cache", "--checkpoint-dir",
              str(tmp_path / "ckpt"), "--no-files"])


def test_cli_pipeline_ranks(gct_path, capsys):
    """ISSUE 5 satellite: --pipeline-ranks (per-rank executables,
    lowest-k-first dispatch feeding the streamed harvest) gets a CLI
    surface; it implies --exec-cache."""
    rc = main([gct_path, "--ks", "2-3", "--restarts", "4",
               "--maxiter", "150", "--no-files", "--pipeline-ranks"])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out


def test_cli_pipeline_ranks_rejects_checkpoint_dir(gct_path, tmp_path):
    # implies --exec-cache, so it inherits its incompatibilities
    with pytest.raises(SystemExit):
        main([gct_path, "--pipeline-ranks", "--checkpoint-dir",
              str(tmp_path / "ckpt"), "--no-files"])


def test_cli_input_cache_bytes(gct_path, capsys):
    """--input-cache-bytes 0 disables input-buffer retention (the run
    still works, nothing stays resident); negatives are a clean usage
    error."""
    from nmfx.data_cache import default_cache

    old = default_cache().max_bytes
    try:
        rc = main([gct_path, "--ks", "2", "--restarts", "3",
                   "--maxiter", "100", "--no-files",
                   "--input-cache-bytes", "0"])
        assert rc == 0
        assert "best k = 2" in capsys.readouterr().out
        assert default_cache().max_bytes == 0
        assert default_cache().stats["entries"] == 0
    finally:
        default_cache().resize(max_bytes=old)
    with pytest.raises(SystemExit):
        main([gct_path, "--input-cache-bytes", "-1", "--no-files"])


def test_cli_serve_smoke(gct_path, tmp_path, capsys):
    """ISSUE 6: --serve-smoke routes the run through the multi-tenant
    serving engine — same summary and output files as the direct path
    (the exactness contract), plus the serve counters and per-request
    spans on stderr."""
    outdir = tmp_path / "served"
    rc = main([gct_path, "--ks", "2", "--restarts", "3",
               "--maxiter", "100", "--outdir", str(outdir),
               "--no-plots", "--serve-smoke"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "serve-smoke: submitted=1 completed=1" in cap.err
    assert "queue-wait=" in cap.err and "latency=" in cap.err
    names = {p.name for p in outdir.iterdir()}
    assert "cophenetic.txt" in names
    assert "consensus.k.2.gct" in names


def test_cli_serve_smoke_rejects_bad_combos(gct_path, tmp_path):
    for argv in (
        # one device: no shard flags
        [gct_path, "--serve-smoke", "--feature-shards", "2",
         "--no-files"],
        # the exec-cache path bypasses the registry resume
        [gct_path, "--serve-smoke", "--checkpoint-dir",
         str(tmp_path / "ckpt"), "--no-files"],
        # served results carry the best restart's factors only
        [gct_path, "--serve-smoke", "--keep-factors", "--no-files"],
        # completion workers harvest on the host
        [gct_path, "--serve-smoke", "--rank-selection", "device",
         "--no-files"],
        # per-k outputs differ from the whole-grid path by float
        # tolerance, which would break the serve exactness contract
        [gct_path, "--serve-smoke", "--grid-exec", "per_k",
         "--no-files"],
    ):
        with pytest.raises(SystemExit):
            main(argv)


def test_cli_observability_flags(gct_path, tmp_path, capsys):
    """ISSUE 10: --trace-out writes a loadable Chrome trace of the run,
    --metrics-out writes Prometheus text exposition, --flight-dir arms
    the crash-dump directory — and the process-wide tracer is disabled
    again after the run (in-process callers must not inherit it)."""
    import json

    from nmfx.obs import flight, trace

    trace.default_tracer().clear()
    trace_path = tmp_path / "trace.json"
    metrics_path = tmp_path / "metrics.prom"
    try:
        rc = main([gct_path, "--ks", "2", "--restarts", "2",
                   "--maxiter", "60", "--no-files",
                   "--trace-out", str(trace_path),
                   "--metrics-out", str(metrics_path),
                   "--flight-dir", str(tmp_path)])
    finally:
        flight.configure(None)
    assert rc == 0
    assert not trace.default_tracer().enabled
    err = capsys.readouterr().err
    assert "structured trace" in err and "metrics written" in err
    chrome = json.loads(trace_path.read_text())
    names = {e["name"] for e in chrome["traceEvents"]
             if e.get("ph") == "X"}
    assert any(n.startswith("solve.") for n in names)
    text = metrics_path.read_text()
    assert "# TYPE nmfx_exec_compile_total counter" in text \
        or "nmfx_data_h2d_transfers_total" in text


def test_cli_perf_report(gct_path, capsys):
    """ISSUE 13: --perf-report runs the sweep with phase timing and
    prints the per-dispatch roofline attribution table (model GFLOP,
    arithmetic intensity, verdict) after the summary."""
    from nmfx.obs import costmodel

    costmodel.reset_perf()
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "60", "--no-files", "--perf-report"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "perf attribution" in out
    assert "verdict" in out
    # attribution ran on the dispatch path (not just an empty table)
    assert costmodel.perf_summary()["kinds"]


def test_cli_sketched_backend(gct_path, capsys):
    """--backend sketched runs end to end and announces the quality
    tag in the summary (ISSUE 12)."""
    rc = main([gct_path, "--ks", "2", "--restarts", "4",
               "--maxiter", "150", "--no-files",
               "--backend", "sketched", "--sketch-dim", "12"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "quality = sketched" in out


def test_cli_screening(gct_path, capsys):
    rc = main([gct_path, "--ks", "2", "--restarts", "6",
               "--maxiter", "150", "--no-files",
               "--screen", "--screen-keep", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    # screening's exact phase IS exact: no quality downgrade announced
    assert "quality = sketched" not in out
    assert "best k = 2" in out


def test_cli_sketched_screen_compose_guards(gct_path, capsys):
    """The ISSUE 12 compose-guards: bit-exact surfaces and the
    statistical engines refuse each other with clear usage errors."""
    cases = [
        # flag plumbing
        (["--backend", "sketched", "--algorithm", "als"],
         "only implemented for"),
        (["--screen"], "requires --screen-keep"),
        (["--screen-keep", "3"], "requires --screen"),
        (["--screen", "--screen-keep", "9", "--restarts", "4"],
         "--screen-keep must be in"),
        (["--sketch-dim", "8"], "only applies to the compressed"),
        (["--screen", "--screen-keep", "2", "--backend", "packed"],
         "vmapped driver"),
        # bit-exact surfaces refuse the statistical contract
        (["--backend", "sketched", "--rank-selection", "device"],
         "STATISTICAL"),
        (["--backend", "sketched", "--checkpoint-dir", "/tmp/nope"],
         "durable ledger"),
        (["--backend", "sketched", "--serve-smoke"], "bit-identical"),
        (["--backend", "sketched", "--exec-cache"], "exec-cacheable"),
        (["--screen", "--screen-keep", "2", "--cache-dir", "/tmp/nope"],
         "exec-cacheable"),
        (["--backend", "sketched", "--grid-exec", "grid"],
         "whole-grid"),
        (["--backend", "sketched", "--feature-shards", "2"],
         "restart-parallel"),
        (["--screen", "--screen-keep", "2", "--keep-factors"],
         "keep-factors"),
    ]
    for extra, needle in cases:
        with pytest.raises(SystemExit):
            main([gct_path, "--no-files"] + extra)
        err = capsys.readouterr().err
        assert needle in err, (extra, needle, err[-500:])


def test_cli_serve_smoke_composes_with_obs_outputs(gct_path, tmp_path,
                                                   capsys):
    """ISSUE 14 satellite: the observability outputs compose with the
    serving path — --trace-out carries the serve spans, --metrics-out
    carries the serve latency histograms, --perf-report includes the
    serve dispatch kind (pre-ISSUE-14 these were only pinned on the
    direct path)."""
    import json

    from nmfx.obs import costmodel, trace

    costmodel.reset_perf()
    trace.default_tracer().clear()
    trace_path = tmp_path / "serve-trace.json"
    metrics_path = tmp_path / "serve-metrics.prom"
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "60", "--no-files", "--serve-smoke",
               "--trace-out", str(trace_path),
               "--metrics-out", str(metrics_path),
               "--perf-report"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "perf attribution" in cap.out
    assert "serve-smoke: submitted=1 completed=1" in cap.err
    chrome = json.loads(trace_path.read_text())
    names = {e["name"] for e in chrome["traceEvents"]
             if e.get("ph") == "X"}
    assert "serve.queue_wait" in names
    assert "serve.dispatch" in names
    # the exported trace carries the cross-process merge anchor
    assert "nmfx_t0_epoch_s" in chrome["metadata"]
    text = metrics_path.read_text()
    assert "nmfx_serve_e2e_seconds" in text
    assert "nmfx_serve_dispatches_total" in text
    # the serve dispatch kind reached the attribution report
    assert "serve" in costmodel.perf_summary()["kinds"]


def test_cli_serve_smoke_fleet_flags(gct_path, tmp_path, capsys):
    """--telemetry-dir publishes the run's snapshots (nmfx-top-ready),
    --metrics-port 0 binds an ephemeral /metrics endpoint, --slo prints
    the burn status — composed on one --serve-smoke run."""
    import json
    import os

    tdir = tmp_path / "telemetry"
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "60", "--no-files", "--serve-smoke",
               "--telemetry-dir", str(tdir),
               "--metrics-port", "0", "--slo"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "serving /metrics on 127.0.0.1:" in cap.err
    assert "slo availability: state=ok" in cap.err
    assert "telemetry published" in cap.err
    snaps = [n for n in os.listdir(tdir) if n.startswith("telemetry_")]
    assert len(snaps) == 1
    payload = json.loads((tdir / snaps[0]).read_text())
    assert payload["role"] == "server"
    assert "nmfx_serve_e2e_seconds" in payload["metrics"]
    # the published ledger renders as a non-empty nmfx-top dashboard
    from nmfx.obs import top

    rc = top.main([str(tdir), "--once", "--stale-after", "600"])
    assert rc == 0
    out = capsys.readouterr().out
    # the published registry is process-cumulative (other in-process
    # runs' requests may precede this one) — pin presence, not counts
    assert "server-" in out and "completed=" in out


def test_cli_fleet_flags_require_serve_smoke(gct_path, tmp_path,
                                             capsys):
    """Compose-guards: the fleet-telemetry flags configure the serving
    engine — without --serve-smoke they are usage errors, never
    silently dropped."""
    cases = [
        (["--telemetry-dir", str(tmp_path / "t")], "--serve-smoke"),
        (["--metrics-port", "0"], "--serve-smoke"),
        (["--slo"], "--serve-smoke"),
        (["--serve-smoke", "--metrics-port", "70000"], "65535"),
    ]
    for extra, needle in cases:
        with pytest.raises(SystemExit):
            main([gct_path, "--no-files"] + extra)
        err = capsys.readouterr().err
        assert needle in err, (extra, needle, err[-500:])


def test_cli_serve_smoke_replicas(gct_path, tmp_path, capsys):
    """ISSUE 15: --replicas routes the smoke request through the
    router + replica pool; the result equals the direct path and the
    routing books are reported. --router-spill-dir pins the pool root
    (heartbeat ledger + spill records land there)."""
    import os

    root = tmp_path / "pool"
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "60", "--no-files", "--serve-smoke",
               "--replicas", "2", "--router-spill-dir", str(root)])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "serve-smoke (router): replicas=2" in cap.err
    assert "completed=1" in cap.err
    beats = [n for n in os.listdir(root)
             if n.startswith("replica_") and n.endswith(".json")]
    assert len(beats) == 2  # both replicas heartbeat into the ledger


def test_cli_replicas_compose_guards(gct_path, tmp_path, capsys):
    """Reject-don't-drop: the service-tier flags are usage errors
    outside their composition."""
    cases = [
        (["--replicas", "2"], "--serve-smoke"),
        (["--serve-smoke", "--replicas", "0"], ">= 1"),
        (["--router-spill-dir", str(tmp_path / "r")], "--replicas"),
        (["--serve-smoke", "--replicas", "2", "--metrics-port", "0"],
         "does not compose"),
    ]
    for extra, needle in cases:
        with pytest.raises(SystemExit):
            main([gct_path, "--no-files"] + extra)
        err = capsys.readouterr().err
        assert needle in err, (extra, needle, err[-500:])


def test_cli_router_main(gct_path, tmp_path, capsys):
    """The nmfx-router entrypoint: a small traffic sample through the
    thread-mode tier, per-request outcomes + router books reported."""
    from nmfx.cli import router_main

    rc = router_main([gct_path, "--replicas", "2", "--requests", "2",
                      "--ks", "2", "--restarts", "2",
                      "--maxiter", "60",
                      "--spill-root", str(tmp_path / "root")])
    assert rc == 0
    cap = capsys.readouterr()
    assert cap.out.count("best k = 2") == 2
    assert "ok on replica-" in cap.err
    assert "submitted=2 completed=2 failed=0" in cap.err


def test_cli_router_main_usage_errors(tmp_path, capsys, gct_path):
    from nmfx.cli import router_main

    with pytest.raises(SystemExit):
        router_main([str(tmp_path / "missing.gct")])
    assert "dataset not found" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        router_main([gct_path, "--replicas", "0"])
    assert ">= 1" in capsys.readouterr().err


# ---------------------------------------------------------------------
# --result-cache-dir (ISSUE 16): request economics from the CLI
# ---------------------------------------------------------------------

def test_cli_result_cache_warm_repeat_bit_identical(gct_path, tmp_path,
                                                    capsys):
    """Second identical run is served from the finished-result cache:
    the saved results are bit-identical, and the cache directory holds
    the entry after run one."""
    import numpy as np

    from nmfx.api import ConsensusResult

    cdir = tmp_path / "rescache"
    argv = [gct_path, "--ks", "2", "--restarts", "3", "--maxiter", "100",
            "--no-files", "--result-cache-dir", str(cdir)]
    assert main(argv + ["--save-result",
                        str(tmp_path / "r1.npz")]) == 0
    entries = [p for p in cdir.iterdir() if p.suffix == ".nmfxres"]
    assert len(entries) == 1
    assert main(argv + ["--save-result",
                        str(tmp_path / "r2.npz")]) == 0
    r1 = ConsensusResult.load(str(tmp_path / "r1.npz"))
    r2 = ConsensusResult.load(str(tmp_path / "r2.npz"))
    assert r1.best_k == r2.best_k == 2
    for k in r1.per_k:
        assert np.asarray(r1.per_k[k].consensus).tobytes() == \
            np.asarray(r2.per_k[k].consensus).tobytes()
    assert capsys.readouterr().out.count("best k = 2") == 2


def test_cli_result_cache_composes_with_serve_smoke(gct_path, tmp_path,
                                                    capsys):
    cdir = tmp_path / "rescache"
    argv = [gct_path, "--ks", "2", "--restarts", "3", "--maxiter", "100",
            "--no-files", "--serve-smoke",
            "--result-cache-dir", str(cdir)]
    assert main(argv) == 0
    assert "result_cache_hits=0" in capsys.readouterr().err
    assert main(argv) == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "result_cache_hits=1" in cap.err


def test_cli_result_cache_composes_with_checkpoint_dir(gct_path,
                                                       tmp_path,
                                                       capsys):
    """Orthogonal durability layers: the ledger persists chunks, the
    result cache persists the finished answer — one run may use both."""
    rc = main([gct_path, "--ks", "2", "--restarts", "4",
               "--maxiter", "100", "--no-files",
               "--checkpoint-dir", str(tmp_path / "ckpt"),
               "--result-cache-dir", str(tmp_path / "rescache")])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out


def test_cli_result_cache_composes_with_replicas(gct_path, tmp_path,
                                                 capsys):
    rc = main([gct_path, "--ks", "2", "--restarts", "2",
               "--maxiter", "60", "--no-files", "--serve-smoke",
               "--replicas", "2",
               "--router-spill-dir", str(tmp_path / "root"),
               "--result-cache-dir", str(tmp_path / "rescache")])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "serve-smoke (router): replicas=2" in cap.err


def test_cli_result_cache_rejects_keep_factors(gct_path, tmp_path,
                                               capsys):
    with pytest.raises(SystemExit):
        main([gct_path, "--keep-factors", "--no-files",
              "--result-cache-dir", str(tmp_path / "rescache")])
    assert "keep-factors" in capsys.readouterr().err


def test_cli_restart_shards(gct_path, capsys):
    """ISSUE 19: --restart-shards N pins the communication-avoiding
    restart axis to exactly N devices (auto uses all 8); results reach
    the same summary as the auto-mesh path."""
    rc = main([gct_path, "--ks", "2", "--restarts", "4",
               "--maxiter", "100", "--no-files",
               "--restart-shards", "4"])
    assert rc == 0
    assert "best k = 2" in capsys.readouterr().out
    # composes with the grid axes into an R x F x S mesh
    rc = main([gct_path, "--ks", "2", "--restarts", "4",
               "--maxiter", "100", "--no-files", "--restart-shards",
               "2", "--feature-shards", "2", "--sample-shards", "2"])
    assert rc == 0


def test_cli_restart_shards_rejects_bad_combos(gct_path, tmp_path):
    for argv in (
        [gct_path, "--restart-shards", "0", "--no-files"],
        [gct_path, "--restart-shards", "16", "--no-files"],  # > devices
        [gct_path, "--restart-shards", "2", "--no-mesh", "--no-files"],
        # the serving scheduler owns one device; mesh-tier serving is
        # per-replica (--replica-mesh)
        [gct_path, "--serve-smoke", "--restart-shards", "2",
         "--no-files"],
        # the tile stream owns one device
        [gct_path, "--restart-shards", "2", "--tile-rows", "16",
         "--no-files"],
        # the cache tier already restart-shards over all devices
        [gct_path, "--exec-cache", "--restart-shards", "2",
         "--no-files"],
    ):
        with pytest.raises(SystemExit):
            main(argv)


def test_cli_replica_mesh_heterogeneous_fleet(gct_path, capsys):
    """ISSUE 19: --replica-mesh makes the serve-smoke pool
    heterogeneous (one plain + one 4-device mesh replica); the priced
    router routes this small request to the 1-device class."""
    rc = main([gct_path, "--ks", "2", "--restarts", "3",
               "--maxiter", "100", "--no-files", "--serve-smoke",
               "--replicas", "2", "--replica-mesh=-,4"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "best k = 2" in cap.out
    assert "class=1" in cap.err  # small request -> plain replica


def test_cli_replica_mesh_rejects_bad_combos(gct_path, capsys):
    # requires the service tier
    with pytest.raises(SystemExit):
        main([gct_path, "--replica-mesh=-,4", "--no-files"])
    assert "pass --serve-smoke --replicas" in capsys.readouterr().err
    # one spec per replica
    with pytest.raises(SystemExit):
        main([gct_path, "--serve-smoke", "--replicas", "3",
              "--replica-mesh=-,4", "--no-files"])
    assert "one entry per replica" in capsys.readouterr().err
    # specs are validated before any replica spawns
    with pytest.raises(SystemExit):
        main([gct_path, "--serve-smoke", "--replicas", "2",
              "--replica-mesh=-,bogus", "--no-files"])
    assert "non-integer axis count" in capsys.readouterr().err
