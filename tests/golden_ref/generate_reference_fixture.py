"""Generate the reference-BINARY golden fixture (reference_mu_fixture.npz).

The reference's own (dormant) validation idea is comparison against a real
reference run (``/root/reference/test_nmf.r:29``). No R interpreter exists
in this image, so — as for BASELINE.md — the reference's C solver is
compiled as-is and driven through ctypes replicating the R ``.C("nmf_mu",
DUP=F)`` protocol exactly (column-major f64 buffers mutated in place,
initial W0/H0 supplied by the caller as the R layer does with ``runif``,
reference ``nmf.r:37-45``). The resulting factors/labels/consensus/rho are
the committed oracle that ``tests/test_reference_binary.py`` asserts nmfx
reproduces — parity against the reference BINARY, not a transliteration.

Protocol notes:

* ``maxiter=300`` (even, fixed): the reference's only live stop needs 200
  stable every-2nd-iteration checks (>= 400 iterations,
  ``nmf_mu.c:253-282``), so neither side can stop early and the
  garbage-driven out-of-bounds stability scan (SURVEY.md Q1) cannot
  influence the run. The pointer-swap double buffering lands results in
  the caller's buffers after an even iteration count (``nmf_mu.c:241-242``).
* W0/H0 ~ numpy ``default_rng(1000*k + r)`` uniform [0,1) f64 — the exact
  protocol the test re-derives.
* Labels use the R layer's observed argmin rule (``nmf.r:128``, quirk Q3);
  consensus is the mean connectivity over restarts (``nmf.r:140-143``);
  rho is computed with SCIPY (average linkage + cophenetic + Pearson — an
  oracle independent of nmfx), unrounded (the reference rounds to 4
  significant digits only when printing, ``nmf.r:172``).

Regenerate (needs /root/reference and a C toolchain; system BLAS/LAPACK/
ARPACK — the exact BLAS only perturbs f64 rounding, the test tolerance
absorbs it):

    cp -r /root/reference/libnmf /tmp/refbuild3
    cd /tmp/refbuild3
    gcc -Wall -Iinclude/ -g -fPIC -shared -o libnmf.so *.c \
        /lib/x86_64-linux-gnu/liblapack.so.3 \
        /lib/x86_64-linux-gnu/libarpack.so.2 \
        /lib/x86_64-linux-gnu/libblas.so.3
    python tests/golden_ref/generate_reference_fixture.py \
        --libnmf /tmp/refbuild3/libnmf.so
"""

import argparse
import ctypes
import os

import numpy as np

KS = (2, 3, 4, 5)
RESTARTS = 10
MAXITER = 300
GCT = "/root/reference/20+20x1000.gct"
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "reference_mu_fixture.npz")


def read_gct(path: str) -> np.ndarray:
    """Minimal GCT v1.2 reader (independent of nmfx.io): skip the 2 header
    lines + the dims line, drop Name/Description columns
    (reference nmf.r:371-377)."""
    with open(path) as f:
        lines = f.read().splitlines()
    n_rows, n_cols = (int(x) for x in lines[1].split("\t")[:2])
    data = [line.split("\t")[2:] for line in lines[3:3 + n_rows]]
    a = np.asarray(data, dtype=np.float64)
    assert a.shape == (n_rows, n_cols), a.shape
    return a


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--libnmf", required=True,
                   help="path to the compiled reference libnmf.so")
    args = p.parse_args()

    from scipy.cluster.hierarchy import average, cophenet
    from scipy.spatial.distance import squareform

    lib = ctypes.CDLL(args.libnmf)
    pd = ctypes.POINTER(ctypes.c_double)
    pi = ctypes.POINTER(ctypes.c_int)
    lib.nmf_mu.restype = ctypes.c_double
    lib.nmf_mu.argtypes = [pd, pd, pd, pi, pi, pi, pi, pd, pd]

    a = read_gct(GCT)
    m, n = a.shape
    out: dict[str, np.ndarray] = {
        "ks": np.asarray(KS), "restarts": np.asarray(RESTARTS),
        "maxiter": np.asarray(MAXITER), "shape": np.asarray([m, n]),
    }
    for k in KS:
        labels_all = []
        for r in range(RESTARTS):
            rng = np.random.default_rng(1000 * k + r)
            w0 = rng.random((m, k))
            h0 = rng.random((k, n))
            af = np.asfortranarray(a)  # fresh per call; `a` is an in-param
            wf = np.asfortranarray(w0)
            hf = np.asfortranarray(h0)
            mi = ctypes.c_int(MAXITER)
            tolx = ctypes.c_double(1e-4)  # dead in nmf_mu (checks
            tolfun = ctypes.c_double(1e-4)  # commented out) but part of
            rc = lib.nmf_mu(  # the .C signature
                af.ctypes.data_as(pd), wf.ctypes.data_as(pd),
                hf.ctypes.data_as(pd),
                ctypes.byref(ctypes.c_int(m)), ctypes.byref(ctypes.c_int(n)),
                ctypes.byref(ctypes.c_int(k)), ctypes.byref(mi),
                ctypes.byref(tolx), ctypes.byref(tolfun))
            assert np.isfinite(rc)
            assert mi.value == MAXITER, (
                f"reference stopped early at {mi.value} — the fixed-budget "
                "protocol is broken")
            labels_all.append(np.argmin(hf, axis=0))  # R rule (Q3)
            out[f"h_k{k}_r{r}"] = np.ascontiguousarray(hf)
            if r == 0:
                out[f"w_k{k}_r0"] = np.ascontiguousarray(wf)
        labels_all = np.stack(labels_all)  # (R, n)
        cons = (labels_all[:, :, None] == labels_all[:, None, :]).mean(0)
        out[f"labels_k{k}"] = labels_all
        out[f"consensus_k{k}"] = cons
        d = squareform(1.0 - cons, checks=False)
        coph = cophenet(average(d))
        out[f"rho_k{k}"] = np.asarray(np.corrcoef(d, coph)[0, 1])
        print(f"k={k}: rho={float(out[f'rho_k{k}']):.6f}")
    np.savez_compressed(OUT, **out)
    print(f"wrote {OUT} ({os.path.getsize(OUT) / 1024:.0f} KiB)")


if __name__ == "__main__":
    main()
