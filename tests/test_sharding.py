"""Multi-device mesh tests on the 8-device virtual CPU platform
(SURVEY.md §2c: restart axis sharded over the mesh, consensus reduced
on-device; conftest.py forces 8 CPU devices via jax.config
jax_platforms/jax_num_cpu_devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.sweep import RESTART_AXIS, default_mesh, sweep, sweep_one_k


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8, f"conftest should give 8 cpu devices, got {devices}"
    return Mesh(np.array(devices), (RESTART_AXIS,))


def test_default_mesh_uses_all_devices():
    m = default_mesh()
    assert m is not None
    assert m.shape[RESTART_AXIS] == 8


def test_sharded_matches_unsharded(low_rank_data, mesh):
    a, _ = low_rank_data
    cfg = SolverConfig(max_iter=200)
    key = jax.random.key(0)
    got = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg, mesh=mesh)
    ref = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg, mesh=None)
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))


def test_uneven_restarts_padded(low_rank_data, mesh):
    # 6 restarts on an 8-device mesh: padded to 8 lanes, surplus discarded
    a, _ = low_rank_data
    cfg = SolverConfig(max_iter=100)
    key = jax.random.key(1)
    got = sweep_one_k(a, key, k=3, restarts=6, solver_cfg=cfg, mesh=mesh)
    assert got.iterations.shape == (6,)
    assert got.labels.shape == (6, a.shape[1])
    ref = sweep_one_k(a, key, k=3, restarts=6, solver_cfg=cfg, mesh=None)
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)


def test_full_sweep_on_mesh(low_rank_data, mesh):
    a, _ = low_rank_data
    out = sweep(a, ConsensusConfig(ks=(2, 3), restarts=16, seed=3),
                SolverConfig(max_iter=150), InitConfig(), mesh)
    for k in (2, 3):
        c = np.asarray(out[k].consensus)
        assert c.shape == (a.shape[1], a.shape[1])
        np.testing.assert_allclose(np.diag(c), 1.0, atol=1e-6)


def test_initial_factors_actually_sharded(low_rank_data, mesh):
    # the sharding constraint must place the restart axis across devices:
    # check the compiled output sharding of a representative batched op
    a, _ = low_rank_data
    shard = NamedSharding(mesh, P(RESTART_AXIS))

    @jax.jit
    def batch_norms(w0s):
        return jnp.sum(w0s**2, axis=(1, 2))

    w0s = jax.device_put(np.ones((8, a.shape[0], 3), np.float32), shard)
    out = batch_norms(w0s)
    assert len(out.sharding.device_set) == 8


# --- feature-axis (tensor-parallel) sharding -------------------------------

from nmfx.sweep import feature_mesh  # noqa: E402


@pytest.mark.parametrize("shape", [(2, 4), (4, 2), (1, 8)])
def test_feature_sharded_matches_unsharded(low_rank_data, shape):
    """Row-sharding A/W over the feature axis (optionally composed with the
    restart axis in a 2-D mesh) must reproduce the unsharded sweep exactly:
    same labels and iteration counts, same consensus, factors to reduction-
    order tolerance."""
    a, _ = low_rank_data
    cfg = SolverConfig(max_iter=150)
    key = jax.random.key(5)
    ref = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg, mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg,
                      mesh=feature_mesh(*shape))
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.dnorms),
                               np.asarray(ref.dnorms), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(got.best_w),
                               np.asarray(ref.best_w), rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got.best_h),
                               np.asarray(ref.best_h), rtol=5e-3, atol=5e-4)


def test_feature_sharded_uneven_m(low_rank_data):
    """m not divisible by the feature shards: zero-row padding must be
    invisible (padded W rows stay exactly zero under the mu update)."""
    a, _ = low_rank_data
    a = a[:53]  # 53 rows across 4 feature shards -> pad to 56
    cfg = SolverConfig(max_iter=100)
    key = jax.random.key(2)
    ref = sweep_one_k(a, key, k=3, restarts=4, solver_cfg=cfg, mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=4, solver_cfg=cfg,
                      mesh=feature_mesh(2, 4))
    assert got.best_w.shape == (53, 3)
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)


def test_feature_sharding_rejects_unsupported_configs(low_rank_data):
    a, _ = low_rank_data
    mesh = feature_mesh(2, 4)
    # only solvers with a sharded update exist on grid meshes: packed mu
    # and kl (als' QR half-steps have no collective formulation here)
    with pytest.raises(ValueError, match="packed mu"):
        sweep_one_k(a, jax.random.key(0), k=2, restarts=4,
                    solver_cfg=SolverConfig(algorithm="als"), mesh=mesh)
    with pytest.raises(ValueError, match="pallas"):
        sweep_one_k(a, jax.random.key(0), k=2, restarts=4,
                    solver_cfg=SolverConfig(backend="pallas"), mesh=mesh)


# --- full 3-axis grid: restarts (dp) x features (tp) x samples (sp) --------

from nmfx.sweep import grid_mesh  # noqa: E402


@pytest.mark.parametrize("shape", [(2, 2, 2), (1, 2, 4), (2, 1, 4),
                                   (1, 1, 8)])
def test_grid_sharded_matches_unsharded(low_rank_data, shape):
    """SUMMA-style 2-D sharding of each factorization (A tiled over
    features x samples, W row-sharded, H column-sharded) composed with the
    restart axis must reproduce the unsharded sweep exactly: same labels
    and iteration counts on every mesh shape."""
    a, _ = low_rank_data
    a = a[:53, :21]  # both dims uneven across every shard count used here
    cfg = SolverConfig(max_iter=120)
    key = jax.random.key(5)
    ref = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg, mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg,
                      mesh=grid_mesh(*shape))
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.dnorms),
                               np.asarray(ref.dnorms), rtol=1e-3)
    assert got.best_w.shape == (53, 3)
    assert got.best_h.shape == (3, 21)
    np.testing.assert_allclose(np.asarray(got.best_w),
                               np.asarray(ref.best_w), rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(got.best_h),
                               np.asarray(ref.best_h), rtol=5e-3, atol=5e-4)


@pytest.mark.parametrize("algorithm,shape", [
    # kl — the solver that *needs* feature/sample sharding (per-restart
    # O(m·n) quotient, solvers/kl.py) — on every mesh shape
    ("kl", (2, 2, 2)), ("kl", (1, 2, 4)), ("kl", (2, 1, 4)),
    ("kl", (1, 1, 8)),
    # the Gram-based family shards through the same psum placement
    ("neals", (2, 2, 2)), ("neals", (1, 2, 4)),
    ("snmf", (2, 2, 2)), ("snmf", (2, 1, 4)),
    ("hals", (2, 2, 2)), ("hals", (1, 2, 4)),
])
def test_grid_solver_sharded_matches_unsharded(low_rank_data, algorithm,
                                               shape):
    """Every GRID_SOLVERS algorithm must reproduce the unsharded sweep on
    grid meshes: labels exactly, factors to f32 reduction-order tolerance.
    Iteration counts are exact for kl (its class-stability stop is robust
    over hundreds of iterations) but may drift for the Gram family —
    neals/snmf stop when a TolX/TolFun threshold crossing lands, and the
    psummed partial Grams' reduction order moves the ~1e-7-level deltas
    near the threshold. On a delta plateau the crossing can slip by many
    checks (measured: up to 18 iterations on one neals restart here), so
    the stopping iteration is only sanity-bounded — the stable observables
    (labels, consensus, residual quality) are asserted tightly."""
    a, _ = low_rank_data
    a = a[:53, :21]  # both dims uneven across every shard count used here
    cfg = SolverConfig(algorithm=algorithm, max_iter=120)
    key = jax.random.key(5)
    ref = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg, mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=8, solver_cfg=cfg,
                      mesh=grid_mesh(*shape))
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    if algorithm == "kl":
        np.testing.assert_array_equal(np.asarray(got.iterations),
                                      np.asarray(ref.iterations))
    else:
        ref_it = np.asarray(ref.iterations, np.int64)
        drift = np.abs(np.asarray(got.iterations, np.int64) - ref_it)
        # pure sanity margin (measured worst case 18; a different XLA
        # build's reduction order could move a plateau crossing further)
        bound = np.maximum(25 * cfg.check_every, (ref_it * 0.5).astype(int))
        assert (drift <= bound).all(), (drift, bound)
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    # atol floor: on the exactly-low-rank fixture the Gram family drives
    # the residual to numerical zero (~1e-4), where relative comparison
    # of two near-zero residuals stopped a few iterations apart is
    # meaningless
    np.testing.assert_allclose(np.asarray(got.dnorms),
                               np.asarray(ref.dnorms), rtol=1e-3,
                               atol=1e-4)
    assert got.best_w.shape == (53, 3)
    assert got.best_h.shape == (3, 21)
    # kl's factors stop at identical iterations (tight bound); the Gram
    # family's may stop a few iterations apart (see above), so its factors
    # differ by the drift of a near-converged trajectory, not by reduction
    # noise — dnorms already pinned equivalent quality. Compare factors
    # only when both sweeps crowned the SAME restart: on this fixture all
    # Gram-family restarts sit at numerically-zero residuals, where
    # reduction noise may legitimately swap the argmin winner (comparing
    # two different random inits' factors would be meaningless)
    ref_best = int(np.argmin(np.asarray(ref.dnorms)))
    got_best = int(np.argmin(np.asarray(got.dnorms)))
    if algorithm == "kl":
        assert ref_best == got_best
    if ref_best == got_best:
        f_rtol, f_atol = (5e-3, 5e-4) if algorithm == "kl" else (3e-2, 3e-3)
        np.testing.assert_allclose(np.asarray(got.best_w),
                                   np.asarray(ref.best_w), rtol=f_rtol,
                                   atol=f_atol)
        np.testing.assert_allclose(np.asarray(got.best_h),
                                   np.asarray(ref.best_h), rtol=f_rtol,
                                   atol=f_atol)


def test_kl_restart_chunk_composes_with_grid_mesh(low_rank_data):
    """restart_chunk on a grid mesh bounds per-device concurrent kl lanes
    (each lane holds an (m_loc × n_loc) quotient) and must not change
    results vs the unchunked grid sweep."""
    a, _ = low_rank_data
    key = jax.random.key(4)
    mesh = grid_mesh(2, 2, 2)
    base_cfg = dict(algorithm="kl", max_iter=100)
    ref = sweep_one_k(a, key, k=3, restarts=12,
                      solver_cfg=SolverConfig(**base_cfg), mesh=mesh)
    got = sweep_one_k(a, key, k=3, restarts=12,
                      solver_cfg=SolverConfig(**base_cfg, restart_chunk=4),
                      mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got.dnorms),
                               np.asarray(ref.dnorms), rtol=1e-4)


@pytest.mark.parametrize("algorithm", ["mu", "kl"])
def test_nndsvd_on_grid_mesh(low_rank_data, algorithm):
    """NNDSVD init on a grid mesh: one deterministic init computed from the
    full matrix at the jit level, sliced to the shards (all restarts
    identical, as in the reference, generatematrix.c:145)."""
    a, _ = low_rank_data
    a = a[:53, :21]
    cfg = SolverConfig(algorithm=algorithm, max_iter=120)
    icfg = InitConfig(method="nndsvd")
    key = jax.random.key(5)
    ref = sweep_one_k(a, key, k=3, restarts=4, solver_cfg=cfg,
                      init_cfg=icfg, mesh=None)
    got = sweep_one_k(a, key, k=3, restarts=4, solver_cfg=cfg,
                      init_cfg=icfg, mesh=grid_mesh(2, 2, 2))
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))
    np.testing.assert_allclose(np.asarray(got.best_w),
                               np.asarray(ref.best_w), rtol=5e-3, atol=5e-4)
    # deterministic init: every restart converged to the same labeling
    labels = np.asarray(got.labels)
    assert (labels == labels[0]).all()


def test_restart_chunking_composes_with_mesh(low_rank_data, mesh):
    """restart_chunk on a restart-sharded mesh: chunk rounds up to the mesh
    size, chunks run sequentially, results match the unchunked mesh sweep."""
    a, _ = low_rank_data
    key = jax.random.key(4)
    ref = sweep_one_k(a, key, k=3, restarts=16,
                      solver_cfg=SolverConfig(algorithm="mu", backend="vmap",
                                              max_iter=100), mesh=mesh)
    got = sweep_one_k(a, key, k=3, restarts=16,
                      solver_cfg=SolverConfig(algorithm="mu", backend="vmap",
                                              max_iter=100, restart_chunk=5),
                      mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got.labels),
                                  np.asarray(ref.labels))
    np.testing.assert_allclose(np.asarray(got.consensus),
                               np.asarray(ref.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got.iterations),
                                  np.asarray(ref.iterations))


def test_place_input_tiles_grid_axes():
    """place_input must tile A over the feature/sample axes (never
    materializing full A per device on a grid mesh), replicate on a
    restart-only mesh, and be idempotent."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nmfx.sweep import FEATURE_AXIS, SAMPLE_AXIS, place_input

    a = np.arange(16 * 12, dtype=np.float32).reshape(16, 12)
    cfg = SolverConfig()
    gm = grid_mesh(2, 2, 2)
    placed = place_input(a, cfg, gm)
    want = NamedSharding(gm, P(FEATURE_AXIS, SAMPLE_AXIS))
    assert placed.sharding.is_equivalent_to(want, 2)
    again = place_input(placed, cfg, gm)
    assert again.sharding.is_equivalent_to(want, 2)
    np.testing.assert_array_equal(np.asarray(again), a)

    rm = Mesh(np.array(jax.devices()), (RESTART_AXIS,))
    rep = place_input(a, cfg, rm)
    assert rep.sharding.is_equivalent_to(NamedSharding(rm, P()), 2)
