"""On-device hclust/cophenetic/cutree (nmfx/ops/hclust_jax.py) against the
host implementation (nmfx/cophenetic.py, itself scipy-validated)."""

import jax.numpy as jnp
import numpy as np
import pytest

from nmfx import cophenetic as host
from nmfx.ops.hclust_jax import average_linkage_jax, rank_selection_jax


def _dist(n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    d = np.linalg.norm(x[:, None] - x[None, :], axis=2)
    return d


@pytest.mark.parametrize("n,seed", [(5, 0), (17, 1), (40, 2)])
def test_linkage_coph_order_match_host(n, seed):
    d = _dist(n, seed)
    ref = host.average_linkage_numpy(d)
    linkage, coph, order, _ = average_linkage_jax(jnp.asarray(d), 1)
    np.testing.assert_allclose(np.asarray(linkage), ref.linkage,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(coph), ref.coph,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(order), ref.order)


@pytest.mark.parametrize("k", [1, 2, 3, 7])
def test_cutree_matches_host(k):
    n = 20
    d = _dist(n, 3)
    ref = host.average_linkage_numpy(d)
    expected = host.cut_tree_numpy(ref.linkage, n, k)
    _, _, _, membership = average_linkage_jax(jnp.asarray(d), k)
    np.testing.assert_array_equal(np.asarray(membership), expected)


@pytest.mark.parametrize("n,seed", [(12, 4), (33, 5)])
def test_rank_selection_matches_host(n, seed):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 3, size=(8, n))
    cons = (labels[:, :, None] == labels[:, None, :]).mean(0)
    k = 3
    rho_ref, memb_ref, order_ref = host.rank_selection(cons, k)
    rho, memb, order = rank_selection_jax(jnp.asarray(cons, jnp.float32), k)
    np.testing.assert_allclose(float(rho), rho_ref, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(memb), memb_ref)
    np.testing.assert_array_equal(np.asarray(order), order_ref)


def test_perfect_consensus_rho_one():
    cons = np.ones((10, 10))
    rho, memb, _ = rank_selection_jax(jnp.asarray(cons), 1)
    assert float(rho) == 1.0
    assert (np.asarray(memb) == 1).all()


def test_tiny_and_edge_shapes():
    d = np.array([[0.0, 1.0], [1.0, 0.0]])
    linkage, coph, order, memb = average_linkage_jax(jnp.asarray(d), 2)
    np.testing.assert_allclose(np.asarray(linkage),
                               [[0.0, 1.0, 1.0, 2.0]])
    assert sorted(np.asarray(order).tolist()) == [0, 1]
    np.testing.assert_array_equal(np.asarray(memb), [1, 2])


def test_pipeline_device_rank_selection(two_group_data):
    """nmfconsensus(rank_selection='device') matches the host path."""
    from nmfx.api import nmfconsensus

    kw = dict(ks=(2, 3), restarts=5, max_iter=300, seed=7)
    ref = nmfconsensus(two_group_data, rank_selection="host", **kw)
    got = nmfconsensus(two_group_data, rank_selection="device", **kw)
    for k in (2, 3):
        # host runs in f64, device in f32: rho may differ at roundoff (and
        # merge order could in principle diverge on adversarial ties, so
        # the structural comparisons stay on this fixed benign fixture)
        assert abs(ref.per_k[k].rho - got.per_k[k].rho) <= 2e-4
        np.testing.assert_array_equal(ref.per_k[k].membership,
                                      got.per_k[k].membership)
        np.testing.assert_array_equal(ref.per_k[k].order,
                                      got.per_k[k].order)
    assert ref.best_k == got.best_k


def test_rank_selection_arg_validated(two_group_data):
    from nmfx.api import nmfconsensus

    with pytest.raises(ValueError, match="rank_selection"):
        nmfconsensus(two_group_data, ks=(2,), restarts=2,
                     rank_selection="gpu")


@pytest.mark.parametrize("method", ["complete", "single"])
def test_other_linkages_match_numpy(method):
    """Device complete/single linkage reproduce the (scipy-cross-tested)
    numpy implementation exactly: heights, cophenetic, order, memberships."""
    from nmfx.cophenetic import cut_tree_numpy, linkage_numpy
    from nmfx.ops.hclust_jax import linkage_jax

    rng = np.random.default_rng(13)
    n, k = 19, 4
    x = rng.uniform(0, 1, (n, 4))
    dist = np.sqrt(((x[:, None] - x[None, :]) ** 2).sum(-1))
    np.fill_diagonal(dist, 0.0)
    ref = linkage_numpy(dist, method)
    linkage, coph, order, membership = linkage_jax(
        jnp.asarray(dist), k, method)
    np.testing.assert_allclose(np.asarray(linkage), ref.linkage, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(coph), ref.coph, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(order), ref.order)
    np.testing.assert_array_equal(np.asarray(membership),
                                  cut_tree_numpy(ref.linkage, n, k))


def test_device_rank_selection_nonaverage_linkage():
    from nmfx.api import nmfconsensus
    from nmfx.datasets import two_group_matrix

    a = two_group_matrix(n_genes=60, n_per_group=6, seed=2)
    host = nmfconsensus(a, ks=(2,), restarts=3, max_iter=150,
                        linkage="complete", use_mesh=False)
    dev = nmfconsensus(a, ks=(2,), restarts=3, max_iter=150,
                       linkage="complete", use_mesh=False,
                       rank_selection="device")
    assert abs(host.per_k[2].rho - dev.per_k[2].rho) < 1e-4
    np.testing.assert_array_equal(host.per_k[2].membership,
                                  dev.per_k[2].membership)
