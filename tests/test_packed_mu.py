"""Parity of the restart-packed MU path (nmfx.ops.packed_mu) with the
generic vmapped driver — same update rule, convergence bookkeeping, freeze
semantics, and sweep outputs, under every backend/mesh combination."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import InitConfig, SolverConfig
from nmfx.ops.packed_mu import (block_diag_mask, mu_packed, pack,
                                residual_norms, unpack_w)
from nmfx.solvers.base import solve
from nmfx.sweep import RESTART_AXIS, sweep_one_k

from jax.sharding import Mesh


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(3)
    m, n, k, r = 96, 28, 3, 6
    a = jnp.asarray(rng.uniform(0.1, 1.0, (m, n)), jnp.float32)
    w0s = jnp.asarray(rng.uniform(0.1, 1.0, (r, m, k)), jnp.float32)
    h0s = jnp.asarray(rng.uniform(0.1, 1.0, (r, k, n)), jnp.float32)
    return a, w0s, h0s


def test_pack_roundtrip(problem):
    _, w0s, h0s = problem
    r = w0s.shape[0]
    wp, hp = pack(w0s, h0s)
    np.testing.assert_array_equal(np.asarray(unpack_w(wp, r)),
                                  np.asarray(w0s))
    np.testing.assert_array_equal(
        np.asarray(hp.reshape(r, h0s.shape[1], -1)), np.asarray(h0s))


def test_block_diag_mask():
    bd = np.asarray(block_diag_mask(3, 2, jnp.float32))
    assert bd.shape == (6, 6)
    for i in range(6):
        for j in range(6):
            assert bd[i, j] == (1.0 if i // 2 == j // 2 else 0.0)


def test_matches_vmapped_driver(problem):
    """Same iterations, stop reasons, and factors as vmap(solve)."""
    a, w0s, h0s = problem
    r = w0s.shape[0]
    cfg = SolverConfig(algorithm="mu", max_iter=300, stable_checks=20)
    ref = jax.vmap(lambda w0, h0: solve(a, w0, h0, cfg))(w0s, h0s)
    got = mu_packed(a, w0s, h0s, cfg)

    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_allclose(np.asarray(ref.w),
                               np.asarray(unpack_w(got.wp, r)),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.h),
                               np.asarray(got.hp.reshape(*ref.h.shape)),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.dnorm), np.asarray(got.dnorm),
                               rtol=1e-4, atol=1e-6)


def test_residual_norms_against_direct(problem):
    """The Gram-trace residual matches the materialized ‖A − WH‖."""
    a, w0s, h0s = problem
    r = w0s.shape[0]
    m, n = a.shape
    wp, hp = pack(w0s, h0s)
    got = np.asarray(residual_norms(a, wp, hp, r))
    for i in range(r):
        direct = np.linalg.norm(
            np.asarray(a) - np.asarray(w0s[i]) @ np.asarray(h0s[i]))
        np.testing.assert_allclose(got[i], direct / np.sqrt(m * n),
                                   rtol=1e-4)


def test_residual_identity_breaks_at_tight_convergence():
    """Why end-of-solve residuals use the direct form: the Gram-trace
    identity's cancellation error swamps the true value once
    dnorm/‖A‖ gets small in f32 (it subtracts terms ~‖A‖²/‖A−WH‖² larger
    than the result), while the direct chunked form stays at f64-truth to
    ~1e-3 relative throughout. Locks VERDICT r2 weak #5 / next #4."""
    from nmfx.ops.packed_mu import residual_norms_direct

    rng = np.random.default_rng(3)
    m, n, k, r = 60, 25, 3, 4
    w = rng.uniform(0.5, 1.5, size=(r, m, k))
    h = rng.uniform(0.5, 1.5, size=(r, k, n))
    recon = np.einsum("rmk,rkn->rmn", w, h)
    a_scale = np.linalg.norm(recon[0])
    for rel in (1e-2, 1e-3, 1e-5):
        noise = rng.standard_normal((m, n))
        a64 = recon[0] + noise * (rel * a_scale / np.linalg.norm(noise))
        truth = np.array([np.linalg.norm(a64 - recon[i]) / np.sqrt(m * n)
                          for i in range(r)])
        a32 = jnp.asarray(a64, jnp.float32)
        w32 = jnp.asarray(w, jnp.float32)
        h32 = jnp.asarray(h, jnp.float32)
        direct = np.asarray(residual_norms_direct(a32, w32, h32, chunk=3))
        # lane 0 is the tightly-converged one; f32 direct keeps ~3 digits
        np.testing.assert_allclose(direct, truth, rtol=2e-3)
        wp, hp = pack(jnp.asarray(w, jnp.float32),
                      jnp.asarray(h, jnp.float32))
        ident = np.asarray(residual_norms(a32, wp, hp, r))
        if rel <= 1e-5:
            # the identity's answer for the converged lane is cancellation
            # noise (order sqrt(eps·‖A‖²/mn) absolute, >10x off here); if
            # this ever starts passing at 2e-3, the direct form can retire
            assert abs(ident[0] - truth[0]) > 10 * abs(
                direct[0] - truth[0])


def test_non_mu_rejected(problem):
    a, w0s, h0s = problem
    with pytest.raises(ValueError, match="mu"):
        mu_packed(a, w0s, h0s, SolverConfig(algorithm="als"))


def test_backend_validation():
    # pg has no dense-batched block (als joined PACKED_ALGORITHMS in
    # round 5, so it no longer serves as the reject case)
    with pytest.raises(ValueError, match="packed"):
        SolverConfig(algorithm="pg", backend="packed")
    with pytest.raises(ValueError, match="backend"):
        SolverConfig(backend="bogus")


def _ksweep(a, backend, mesh, restarts=10, label_rule="argmax"):
    cfg = SolverConfig(algorithm="mu", max_iter=200, stable_checks=15,
                       backend=backend)
    return sweep_one_k(a, jax.random.key(11), k=3, restarts=restarts,
                       solver_cfg=cfg, init_cfg=InitConfig(),
                       label_rule=label_rule, mesh=mesh)


@pytest.mark.parametrize("label_rule", ["argmax", "argmin"])
def test_sweep_backend_parity(two_group_data, label_rule):
    """backend='packed' and backend='vmap' produce identical sweeps."""
    ref = _ksweep(two_group_data, "vmap", None, label_rule=label_rule)
    got = _ksweep(two_group_data, "packed", None, label_rule=label_rule)
    np.testing.assert_array_equal(np.asarray(ref.labels),
                                  np.asarray(got.labels))
    np.testing.assert_allclose(np.asarray(ref.consensus),
                               np.asarray(got.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_allclose(np.asarray(ref.best_w),
                               np.asarray(got.best_w), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.best_h),
                               np.asarray(got.best_h), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("restarts", [16, 10])  # even shards / padded lanes
def test_sweep_mesh_parity(two_group_data, restarts):
    """The shard_map packed sweep equals the single-device packed sweep,
    including when padding lanes must be masked out of the reduction."""
    mesh = Mesh(np.array(jax.devices()), (RESTART_AXIS,))
    ref = _ksweep(two_group_data, "packed", None, restarts=restarts)
    got = _ksweep(two_group_data, "packed", mesh, restarts=restarts)
    np.testing.assert_array_equal(np.asarray(ref.labels),
                                  np.asarray(got.labels))
    np.testing.assert_allclose(np.asarray(ref.consensus),
                               np.asarray(got.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_allclose(np.asarray(ref.dnorms),
                               np.asarray(got.dnorms), rtol=1e-4, atol=1e-6)
    for f in ("best_w", "best_h"):
        np.testing.assert_allclose(np.asarray(getattr(ref, f)),
                                   np.asarray(getattr(got, f)),
                                   rtol=2e-4, atol=2e-5)
    assert np.asarray(got.consensus).shape[0] == two_group_data.shape[1]


def test_pallas_backend_matches_packed(problem):
    """backend='pallas' (interpret mode off-TPU) reproduces the packed
    iteration: same convergence path and factors to matmul tolerance."""
    a, w0s, h0s = problem
    r = w0s.shape[0]
    cfg_ref = SolverConfig(algorithm="mu", max_iter=40, stable_checks=5,
                           backend="packed")
    cfg_pl = SolverConfig(algorithm="mu", max_iter=40, stable_checks=5,
                          backend="pallas")
    ref = mu_packed(a, w0s, h0s, cfg_ref)
    got = mu_packed(a, w0s, h0s, cfg_pl)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_allclose(np.asarray(unpack_w(ref.wp, r)),
                               np.asarray(unpack_w(got.wp, r)),
                               rtol=5e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(ref.hp), np.asarray(got.hp),
                               rtol=5e-3, atol=1e-4)


def test_pallas_m_padding(problem):
    """m not a multiple of the kernel tile: zero-row padding must be
    invariant and invisible in the outputs."""
    a, w0s, h0s = problem  # m=96 -> block_m=96? force an uneven tile
    m = 70
    a2 = a[:m]
    w2 = w0s[:, :m, :]
    cfg = SolverConfig(algorithm="mu", max_iter=30, backend="pallas")
    got = mu_packed(a2, w2, h0s, cfg)
    assert got.wp.shape[0] == m
    ref = mu_packed(a2, w2, h0s,
                    SolverConfig(algorithm="mu", max_iter=30,
                                 backend="packed"))
    np.testing.assert_allclose(np.asarray(got.hp), np.asarray(ref.hp),
                               rtol=5e-3, atol=1e-4)


def test_bf16_operand_step_close_to_f32(problem):
    """The bandwidth-lean _step branch (A pre-truncated to bf16, factors cast
    per GEMM; taken by mu_packed on TPU under matmul_precision='bfloat16')
    tracks the f32-operand iteration within bf16 rounding and keeps the
    f32 carry dtypes."""
    from nmfx.ops.packed_mu import PackedState, _step, block_diag_mask, pack

    a, w0s, h0s = problem
    r, _, k = w0s.shape
    n = h0s.shape[2]
    cfg = SolverConfig(algorithm="mu")
    wp, hp = pack(w0s, h0s)
    bd = block_diag_mask(r, k, jnp.float32)
    state = PackedState(
        wp=wp, hp=hp, wp_prev=wp, hp_prev=hp,
        iteration=jnp.zeros((), jnp.int32),
        classes=jnp.full((r, n), -1, jnp.int32),
        stable=jnp.zeros((r,), jnp.int32),
        done=jnp.zeros((r,), bool),
        done_iter=jnp.zeros((r,), jnp.int32),
        stop_reason=jnp.zeros((r,), jnp.int32))
    ref = state
    got = state
    for _ in range(5):
        ref = _step(a, bd, ref, cfg, r, check=False)
        got = _step(a.astype(jnp.bfloat16), bd, got, cfg, r, check=False)
    assert got.wp.dtype == jnp.float32
    assert got.hp.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got.hp), np.asarray(ref.hp),
                               rtol=0.1, atol=0.02)
    np.testing.assert_allclose(np.asarray(got.wp), np.asarray(ref.wp),
                               rtol=0.1, atol=0.02)
