"""Streamed-harvest determinism (ISSUE 5 tentpole): the pipelined
warm path — per-rank device→host copies and host rank selection running
in worker threads while later ranks still solve — must be BIT-IDENTICAL
to the strictly phase-sequential path on every engine family reachable
on CPU. Overlap buys wall time, never drift: both paths consume the
same device outputs through the same ``device_get`` and the same
``api._build_k_result`` host math, and these tests pin that equality
field by field. Plus the pipeline's own mechanics (double-submit,
error propagation, close idempotence, overlap-phase accounting)."""

import numpy as np
import pytest

from nmfx.api import nmfconsensus
from nmfx.harvest import HarvestPipeline
from nmfx.profiling import Profiler

KS = (2, 3)
RESTARTS = 2
MAX_ITER = 30


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    # <= 60x20: the smallest shape with two planted groups (tier-1
    # budget discipline, ISSUE 5 satellite)
    return two_group_matrix(n_genes=60, n_per_group=10, seed=3)


def _run(data, harvest, *, algorithm="mu", backend="auto",
         grid_exec="auto", **kw):
    from nmfx.config import SolverConfig

    scfg = SolverConfig(algorithm=algorithm, backend=backend,
                        max_iter=MAX_ITER)
    return nmfconsensus(data, ks=KS, restarts=RESTARTS, seed=11,
                        solver_cfg=scfg, grid_exec=grid_exec,
                        use_mesh=False, harvest=harvest, **kw)


def assert_results_bit_equal(streamed, sequential):
    """Every per-rank field the KResult carries, bitwise."""
    assert set(streamed.per_k) == set(sequential.per_k)
    for k in sequential.per_k:
        s, q = streamed.per_k[k], sequential.per_k[k]
        assert s.consensus.dtype == q.consensus.dtype
        assert np.array_equal(s.consensus, q.consensus), f"consensus k={k}"
        assert s.rho == q.rho, f"rho k={k}"
        assert np.array_equal(s.membership, q.membership), f"membership k={k}"
        assert np.array_equal(s.order, q.order), f"order k={k}"
        assert np.array_equal(s.iterations, q.iterations), f"iterations k={k}"
        assert np.array_equal(s.stop_reasons, q.stop_reasons), (
            f"stop_reasons k={k}")
        assert np.array_equal(s.dnorms, q.dnorms), f"dnorms k={k}"
        assert s.dispersion == q.dispersion, f"dispersion k={k}"
        assert np.array_equal(s.best_w, q.best_w), f"best_w k={k}"
        assert np.array_equal(s.best_h, q.best_h), f"best_h k={k}"


# one representative per engine family reachable on CPU: the whole-grid
# engine (mu routes through the packed/scheduled machinery under
# grid_exec auto), the vmapped per-k loop, and the packed per-k family
# on a second algorithm
@pytest.mark.parametrize("algorithm,backend,grid_exec", [
    ("mu", "auto", "auto"),      # whole-grid engine
    ("mu", "vmap", "per_k"),     # vmapped per-k loop
    ("hals", "packed", "auto"),  # packed family, non-mu block
])
def test_streamed_equals_sequential(small_data, algorithm, backend,
                                    grid_exec):
    streamed = _run(small_data, "streamed", algorithm=algorithm,
                    backend=backend, grid_exec=grid_exec)
    sequential = _run(small_data, "sequential", algorithm=algorithm,
                      backend=backend, grid_exec=grid_exec)
    assert_results_bit_equal(streamed, sequential)


@pytest.mark.slow
@pytest.mark.parametrize("algorithm,backend,grid_exec", [
    ("als", "auto", "per_k"),
    ("kl", "packed", "auto"),
])
def test_streamed_equals_sequential_more_engines(small_data, algorithm,
                                                 backend, grid_exec):
    streamed = _run(small_data, "streamed", algorithm=algorithm,
                    backend=backend, grid_exec=grid_exec)
    sequential = _run(small_data, "sequential", algorithm=algorithm,
                      backend=backend, grid_exec=grid_exec)
    assert_results_bit_equal(streamed, sequential)


def test_streamed_run_to_run_deterministic(small_data):
    """Threaded harvest twice over the same inputs: no ordering or
    float-reassociation effect may leak into the results."""
    a = _run(small_data, "streamed")
    b = _run(small_data, "streamed")
    assert_results_bit_equal(a, b)


def test_streamed_through_exec_cache_pipeline_ranks(small_data):
    """The fully-streamed serving shape: per-rank executables
    (``pipeline_ranks``) feeding the harvest pipeline — still exactly
    the sequential assembly of the SAME per-rank engine."""
    from nmfx.config import ExecCacheConfig
    from nmfx.exec_cache import ExecCache

    cache = ExecCache(ExecCacheConfig(pipeline_ranks=True))
    streamed = _run(small_data, "streamed", exec_cache=cache)
    sequential = _run(small_data, "sequential", exec_cache=cache)
    assert_results_bit_equal(streamed, sequential)


def test_streamed_overlap_phases_recorded(small_data):
    """The harvest workers credit their walls to the overlap phases the
    e2e accounting audits (xfer.d2h_overlap, post.rank_selection) —
    the r05 failure was exactly this work running outside every phase."""
    prof = Profiler()
    with prof:
        _run(small_data, "streamed", profiler=prof)
    assert prof.phases["xfer.d2h_overlap"].count >= len(KS)
    assert prof.phases["post.rank_selection"].count >= len(KS)
    assert prof.phases["post.rank_selection"].seconds > 0
    # and they are classed as overlapped, so the sequential phase sum
    # (the audit's phase-sum-vs-wall book) does not double-count them
    assert prof.phases["post.rank_selection"].overlapped
    audit = prof.audit()
    assert audit["overlap_s"] > 0


def test_device_rank_selection_implies_sequential(small_data):
    """harvest='streamed' + rank_selection='device' falls back to the
    sequential assembly (the clustering already overlaps on-device);
    results must match the host path to float tolerance as before."""
    r = _run(small_data, "streamed", rank_selection="device")
    assert set(r.per_k) == set(KS)
    for k in KS:
        assert r.per_k[k].consensus.shape[0] == small_data.shape[1]


def test_harvest_rejects_bad_mode(small_data):
    with pytest.raises(ValueError, match="harvest"):
        _run(small_data, "overlapped")


# ---------------------------------------------------------------- pipeline
# mechanics, no solver involved

def test_pipeline_double_submit_rejected():
    from nmfx.sweep import KSweepOutput

    pipe = HarvestPipeline()
    # perfect two-cluster consensus: rank selection is well-defined
    cons = np.kron(np.eye(2), np.ones((2, 2))).astype(np.float32)
    out = KSweepOutput(
        consensus=cons, labels=None,
        iterations=np.array([1]), dnorms=np.array([0.0]),
        stop_reasons=np.array([0]), best_w=None, best_h=None,
        all_w=None, all_h=None)
    pipe.submit(2, out)
    with pytest.raises(ValueError, match="submitted twice"):
        pipe.submit(2, out)
    pipe.results()


def test_pipeline_worker_error_propagates():
    pipe = HarvestPipeline()
    pipe.submit(2, None)  # no ._replace -> worker raises
    with pytest.raises(AttributeError):
        pipe.results()


def test_pipeline_close_idempotent_and_rejects_late_submit():
    pipe = HarvestPipeline()
    pipe.close()
    pipe.close()
    with pytest.raises(RuntimeError, match="closed"):
        pipe.submit(2, object())


def test_pipeline_workers_validation():
    with pytest.raises(ValueError, match="workers"):
        HarvestPipeline(workers=0)


def test_pipeline_failed_worker_spawn_strands_nothing(monkeypatch):
    """NMFX014 regression (the stranded-future gap the concurrency
    lint surfaced): a Thread spawn that fails on the first submit must
    raise out of submit() with NOTHING published — before the fix the
    future was registered first, so a caller that caught the error and
    went on to results() hung forever on a waiter no worker would ever
    resolve."""
    import threading

    pipe = HarvestPipeline()

    def boom(*a, **kw):
        raise RuntimeError("no threads today")

    monkeypatch.setattr(threading, "Thread", boom)
    with pytest.raises(RuntimeError, match="no threads today"):
        pipe.submit(2, object())
    # the failed submit left no stranded waiter and no orphaned output
    assert pipe._futures == {}
    assert pipe._outs == {}
    monkeypatch.undo()
    # results() terminates immediately instead of hanging
    assert pipe.results() == {}
