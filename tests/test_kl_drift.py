"""kl packed-grid vs vmapped-default consensus drift at high k (round 6,
pinning the round-5 finding).

Round 5 measured (RESULTS.md "kl same-range pair"): at the north-star
shape the whole-grid kl engine (``backend="packed"``) reproduces the
vmapped default's consensus exactly at k<=4, while at k=5/6 — ranks
above the benchmark matrix's 4-group structure — surplus-cluster
near-ties split differently between the engines' reduction orders and
max|dC| reached 0.25 at R=20 (rho identical, iteration ratios
0.95–0.97). This is the over-clustering trajectory-drift class the
hardware gate bounds, not a corruption: it appears exactly when k
exceeds the data's structure.

This test pins the band at a gate-scale shape (the north-star-scale
measurement lives in RESULTS.md round 5; ``SolverConfig.backend``'s
docstring carries the user-facing guidance). The bound is asserted in
RESTART-EQUIVALENTS (mean|dC|*R), the normalization that makes one band
correct at any restart count (see bench.py's ``compare``).
"""

import numpy as np
import pytest

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.sweep import sweep

R = 8


@pytest.fixture(scope="module")
def engines_out():
    a = grouped_matrix(400, (20, 20, 20, 20), effect=2.0, seed=0)
    out = {}
    for name, backend, grid_exec in (("vmap", "auto", "per_k"),
                                     ("packed", "packed", "grid")):
        scfg = SolverConfig(algorithm="kl", max_iter=400, backend=backend)
        out[name] = sweep(a, ConsensusConfig(ks=(4, 5, 6), restarts=R,
                                             grid_exec=grid_exec),
                          scfg, InitConfig(), None)
    return out


@pytest.mark.parametrize("k", [5, 6])
def test_kl_packed_high_k_drift_bounded(engines_out, k):
    """The k=5/6 over-clustering drift stays inside the hardware gate's
    bands: mean|dC|*R <= 0.6 restart-equivalents, and iteration counts
    within the gate's 1.6x ratio."""
    v, p = engines_out["vmap"][k], engines_out["packed"][k]
    dc = np.abs(np.asarray(v.consensus) - np.asarray(p.consensus))
    assert dc.mean() * R <= 0.6, dc.mean() * R
    # max|dC|*R: a handful of boundary samples may disagree across a few
    # restarts (round 5 measured max|dC| = 0.25 at R=20 -> 5
    # restart-equivalents); anything approaching all-R disagreement on
    # many pairs would be the round-3 corruption class instead
    assert dc.max() * R <= 6.0, dc.max() * R
    iv = float(np.asarray(v.iterations).mean())
    ip = float(np.asarray(p.iterations).mean())
    assert 1 / 1.6 <= ip / iv <= 1.6, (ip, iv)


def test_kl_packed_low_k_agreement(engines_out):
    """At k within the data's structure (k=4 on 4-group data) the two
    engines' consensus agrees tightly — the drift is a high-k
    phenomenon, which is what makes it safe to document rather than
    fix."""
    v, p = engines_out["vmap"][4], engines_out["packed"][4]
    dc = np.abs(np.asarray(v.consensus) - np.asarray(p.consensus))
    assert dc.mean() * R <= 0.25, dc.mean() * R
