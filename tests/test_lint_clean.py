"""Tier-1 gate: the full analyzer over ``nmfx/`` reports ZERO
unsuppressed findings with an EMPTY baseline (ISSUE 3 acceptance).

This is the enforcement point for every contract class the linter
encodes: adding a SolverConfig field that misses the fingerprint, an
env read reachable from jitted code, a key reuse, a read-after-donate,
or an engine that stops tracing f32-clean under x64 turns this test
red — at lint time, not in a hardware sweep three rounds later.
"""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "nmfx")


def test_nmfx_tree_lint_clean():
    from nmfx.analysis import active, run

    findings = run([PKG], jaxpr=True)
    errors = active(findings, "error")
    warnings = active(findings, "warning")
    assert not errors, "\n".join(f.render() for f in errors)
    assert not warnings, "\n".join(f.render() for f in warnings)
    # the shipped-baseline policy IS the empty baseline: nothing above
    # relied on one (no baseline was passed), and no finding survived
    # as suppressed without the required reason (parse_suppressions
    # rejects reasonless ignores as NMFX000, which `active` would carry)


def test_cli_entrypoint_exits_zero():
    """``python -m nmfx.analysis nmfx/`` (the documented invocation)
    exits 0 on the shipped tree. AST layer only: the jaxpr layer runs
    in-process above; a second trace of every engine in a subprocess
    would double the cost for no added coverage."""
    proc = subprocess.run(
        [sys.executable, "-m", "nmfx.analysis", PKG, "--no-jaxpr"],
        capture_output=True, text=True, timeout=240, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout


def test_ruff_clean_if_available():
    """Generic lint stays delegated to ruff (pyproject [tool.ruff]) so
    nmfx-lint rules stay domain-focused; the container image may not
    ship ruff, in which case this gate runs wherever it is installed."""
    ruff = shutil.which("ruff")
    if ruff is None:
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run([ruff, "check", "nmfx", "tests", "bench.py"],
                          capture_output=True, text=True, timeout=240,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
