"""I/O layer tests (reference readers/writer: nmf.r:261-408)."""

import os

import numpy as np
import pytest

from nmfx.io import read_dataset, read_gct, read_res, write_gct

REFERENCE_GCT = "/root/reference/20+20x1000.gct"


def test_gct_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    vals = rng.uniform(0, 10, size=(7, 5))
    path = str(tmp_path / "x.gct")
    write_gct(vals, path, row_names=[f"g{i}" for i in range(7)],
              col_names=[f"s{j}" for j in range(5)])
    ds = read_gct(path)
    np.testing.assert_allclose(ds.values, vals, rtol=1e-6)
    assert ds.row_names == [f"g{i}" for i in range(7)]
    assert ds.col_names == [f"s{j}" for j in range(5)]


def test_read_dataset_dispatch(tmp_path):
    vals = np.ones((2, 3))
    path = str(tmp_path / "y.GCT")
    write_gct(vals, path)
    ds = read_dataset(path)
    assert ds.shape == (2, 3)
    with pytest.raises(ValueError):
        read_dataset(str(tmp_path / "z.txt"))


def test_read_res(tmp_path):
    path = str(tmp_path / "x.res")
    with open(path, "w") as f:
        f.write("Description\tAccession\tsampA\t\tsampB\t\n")
        f.write("\t\tdescA\tdescB\n")
        f.write("2\n")
        f.write("gene one\tG1\t1.5\tP\t2.5\tA\n")
        f.write("gene two\tG2\t3.0\tP\t4.0\tM\n")
    ds = read_res(path)
    assert ds.col_names == ["sampA", "sampB"]
    assert ds.row_names == ["G1", "G2"]
    np.testing.assert_allclose(ds.values, [[1.5, 2.5], [3.0, 4.0]])


@pytest.mark.skipif(not os.path.exists(REFERENCE_GCT),
                    reason="reference fixture not mounted")
def test_reference_fixture_dims():
    # the bundled dataset is 1000 genes x 40 samples (SURVEY.md, GCT header)
    ds = read_gct(REFERENCE_GCT)
    assert ds.shape == (1000, 40)
    assert np.isfinite(ds.values).all()


def test_write_gct_creates_dirs(tmp_path):
    path = str(tmp_path / "sub" / "dir" / "o.gct")
    write_gct(np.zeros((1, 1)), path)
    assert os.path.exists(path)


def test_write_gct_shape_validation(tmp_path):
    with pytest.raises(ValueError):
        write_gct(np.zeros((2, 2)), str(tmp_path / "bad.gct"), row_names=["a"])


@pytest.fixture(params=["native", "numpy"])
def io_backend(request, monkeypatch):
    """Run I/O tests under both the native C++ path and the numpy fallback."""
    from nmfx import native

    if request.param == "native":
        if not native.available():
            pytest.skip("native library unavailable")
    else:
        monkeypatch.setattr(native, "available", lambda: False)
    return request.param


def test_gct_lenient_parsing(tmp_path, io_backend):
    """Both parse paths accept what the reference reader accepted: extra
    trailing fields (ignored), leading '+', and '#' inside names."""
    p = str(tmp_path / "lenient.gct")
    with open(p, "w") as f:
        f.write("#1.2\n2\t3\nName\tDescription\ts1\ts2\ts3\n")
        f.write("g#1\tdesc # hash\t1.5\t+2\t3\textra\tfields\n")
        f.write("g2\td\t4\t5e-1\t6.25\n")
    ds = read_gct(p)
    np.testing.assert_array_equal(ds.values, [[1.5, 2.0, 3.0],
                                              [4.0, 0.5, 6.25]])
    assert ds.row_names == ["g#1", "g2"]


def test_write_gct_backends_byte_identical(tmp_path, monkeypatch):
    """The numpy fallback writer must produce the same bytes as the native
    std::to_chars path — a written GCT must not depend on whether the C++
    library is built. Property-tested across the magnitude range where
    Python repr and to_chars choose notation differently (repr switches to
    scientific only outside [1e-4, 1e16); to_chars picks whichever form is
    shorter), plus boundary values."""
    from nmfx import native

    if not native.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    rand = (rng.uniform(-1, 1, 120)
            * 10.0 ** rng.integers(-25, 25, size=120))
    special = np.array([0.1, 1.0, 2.5e-17, 123456.0, -0.0, 7.25,
                        1e10, -1e10, 0.0001, 1e-4, 9.999e15, 1e16,
                        123456789.0, 5e-324, 1.7976931348623157e308,
                        1e100, -3.141592653589793e-100])
    vals = np.concatenate([rand, special]).reshape(-1, 1)
    kw = dict(row_names=[f"r{i}" for i in range(len(vals))],
              col_names=["x"])
    p_native = str(tmp_path / "n.gct")
    write_gct(vals, p_native, **kw)
    monkeypatch.setattr(native, "available", lambda: False)
    p_numpy = str(tmp_path / "f.gct")
    write_gct(vals, p_numpy, **kw)
    with open(p_native, "rt") as f1, open(p_numpy, "rt") as f2:
        for line1, line2 in zip(f1, f2):
            assert line1 == line2, (line1, line2)


def test_gct_roundtrip_both_backends(tmp_path, io_backend):
    rng = np.random.default_rng(3)
    vals = rng.uniform(0, 10, size=(9, 4))
    path = str(tmp_path / "rt.gct")
    write_gct(vals, path, row_names=[f"r{i}" for i in range(9)],
              col_names=list("abcd"))
    ds = read_gct(path)
    np.testing.assert_array_equal(ds.values, vals)


def test_write_gct_descriptions_validated(tmp_path):
    with pytest.raises(ValueError, match="descriptions"):
        write_gct(np.ones((3, 2)), str(tmp_path / "x.gct"),
                  row_names=list("abc"), col_names=list("xy"),
                  descriptions=["only-one"])


def test_gct_crlf_line_endings(tmp_path, io_backend):
    """Windows line endings: values, row names, AND column names parse
    clean (no stray carriage returns)."""
    p = str(tmp_path / "crlf.gct")
    with open(p, "wb") as f:
        f.write(b"#1.2\r\n2\t3\r\nName\tDescription\ts1\ts2\ts3\r\n")
        f.write(b"g1\td\t1.5\t2\t3\r\n")
        f.write(b"g2\td\t4\t5\t6.25\r\n")
    ds = read_gct(p)
    np.testing.assert_array_equal(ds.values, [[1.5, 2.0, 3.0],
                                              [4.0, 5.0, 6.25]])
    assert ds.row_names == ["g1", "g2"]
    assert ds.col_names == ["s1", "s2", "s3"]


# ---------------------------------------------------------------------
# atlas-scale ingestion (ISSUE 17): streamed GCT, .mtx, .csr.npz
# ---------------------------------------------------------------------

def test_gct_streamed_chunks_match_monolithic(tmp_path, io_backend):
    rng = np.random.default_rng(5)
    vals = rng.uniform(0, 10, size=(97, 13))
    p = str(tmp_path / "big.gct")
    write_gct(vals, p, row_names=[f"r{i}" for i in range(97)],
              col_names=[f"c{j}" for j in range(13)])
    whole = read_gct(p)
    chunked = read_gct(p, chunk_rows=8)
    np.testing.assert_array_equal(chunked.values, whole.values)
    assert chunked.row_names == whole.row_names
    assert chunked.col_names == whole.col_names


def test_gct_streamed_parse_peak_ram_bounded(tmp_path, io_backend):
    """The streamed loader's contract: peak host RAM during parse stays
    pinned near the preallocated values array plus ONE row batch — it
    never holds the full text AND the full array (the 2x-file-size
    failure mode the row-chunked parse removes)."""
    import tracemalloc

    rng = np.random.default_rng(6)
    vals = rng.uniform(0, 10, size=(600, 40))
    p = str(tmp_path / "peak.gct")
    write_gct(vals, p)
    fsize = os.path.getsize(p)
    values_bytes = vals.nbytes
    tracemalloc.start()
    ds = read_gct(p, chunk_rows=16)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    np.testing.assert_array_equal(ds.values, vals)
    # monolithic parsing holds text + array: >= values + file size.
    # Streamed must stay well under that (array + one 16-row batch +
    # bookkeeping).
    assert peak < values_bytes + fsize, (peak, values_bytes, fsize)


def test_gct_truncated_file_row_count_error(tmp_path, io_backend):
    vals = np.ones((10, 3))
    p = str(tmp_path / "t.gct")
    write_gct(vals, p)
    with open(p) as f:
        lines = f.readlines()
    with open(p, "w") as f:
        f.writelines(lines[:-2])  # drop 2 data rows, keep the header
    with pytest.raises(ValueError, match="found 8 data rows"):
        read_gct(p)


def test_mtx_roundtrip_and_dispatch(tmp_path):
    from nmfx.io import read_mtx
    from nmfx.sparse import SparseMatrix

    rng = np.random.default_rng(7)
    dense = rng.uniform(1, 5, size=(12, 9))
    dense[rng.random(dense.shape) < 0.7] = 0.0
    sp = SparseMatrix.from_dense(dense)
    p = str(tmp_path / "m.mtx")
    rows = np.repeat(np.arange(12), np.diff(sp.indptr))
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix coordinate real general\n")
        f.write("% a comment line\n")
        f.write(f"12 9 {sp.nnz}\n")
        for r, c, v in zip(rows, sp.indices, sp.data):
            f.write(f"{r + 1} {c + 1} {float(v)!r}\n")
    got = read_mtx(p)
    assert got.fingerprint() == sp.fingerprint()
    via_dispatch = read_dataset(p)
    assert isinstance(via_dispatch, SparseMatrix)
    assert via_dispatch.fingerprint() == sp.fingerprint()


def test_mtx_duplicate_entries_summed(tmp_path):
    from nmfx.io import read_mtx

    p = str(tmp_path / "dup.mtx")
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix coordinate real general\n")
        f.write("2 2 3\n")
        f.write("1 1 1.5\n1 1 0.5\n2 2 3.0\n")
    got = read_mtx(p)
    np.testing.assert_array_equal(got.toarray(), [[2.0, 0.0],
                                                  [0.0, 3.0]])


def test_mtx_rejects_unsupported_banner(tmp_path):
    from nmfx.io import read_mtx

    p = str(tmp_path / "bad.mtx")
    with open(p, "w") as f:
        f.write("%%MatrixMarket matrix array real general\n1 1\n0\n")
    with pytest.raises(ValueError, match="[Mm]atrix[Mm]arket"):
        read_mtx(p)


def test_csr_npz_roundtrip_and_dispatch(tmp_path):
    from nmfx.datasets import make_sparse_design
    from nmfx.io import read_csr_npz, write_csr_npz
    from nmfx.sparse import SparseMatrix

    sp = make_sparse_design(40, 15, k=2, density=0.2, seed=8)
    p = str(tmp_path / "sub" / "x.csr.npz")
    write_csr_npz(sp, p)
    got = read_csr_npz(p)
    assert got.fingerprint() == sp.fingerprint()
    via_dispatch = read_dataset(p)
    assert isinstance(via_dispatch, SparseMatrix)
    assert via_dispatch.fingerprint() == sp.fingerprint()


def test_csr_npz_rejects_foreign_bundle(tmp_path):
    from nmfx.io import read_csr_npz

    p = str(tmp_path / "bad.csr.npz")
    np.savez(p, wrong=np.ones(3))
    with pytest.raises(ValueError, match="CSR bundle"):
        read_csr_npz(p)
