"""The bench hardware-truth gate's plausibility rules (bench.py).

These run on synthetic stop records — no solver execution — and lock the
exact failure modes of the round-3 incident (BENCH_r03 recorded
mean_iters_per_k=2.0 from a broken kernel and nothing noticed): a
physically-impossible record must produce problems, and every legitimate
record class (TolX solvers, low-maxiter smoke runs, healthy mu) must
not.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _integrity_problems  # noqa: E402
from nmfx.config import SolverConfig  # noqa: E402
from nmfx.solvers.base import StopReason  # noqa: E402

CS = int(StopReason.CLASS_STABLE)
TX = int(StopReason.TOL_X)
MI = int(StopReason.MAX_ITER)
#: check_every * (stable_checks + 1) at SolverConfig defaults — the gate's
#: minimum credible class-stable stop; boundary assertions reference it so
#: a default change moves the tests with it
FLOOR = (SolverConfig().check_every
         * (SolverConfig().stable_checks + 1))


def rec(iters, stops):
    return ({2: np.asarray(iters)}, {2: np.asarray(stops)})


def test_healthy_mu_record_passes():
    its, stops = rec([FLOOR + 48, FLOOR + 118, FLOOR + 298, 8000],
                     [CS, CS, CS, MI])
    assert _integrity_problems(SolverConfig(), its, stops) == []


def test_class_stable_below_floor_is_impossible():
    its, stops = rec([FLOOR - 300, FLOOR + 118], [CS, CS])
    problems = _integrity_problems(SolverConfig(), its, stops)
    assert any("CLASS_STABLE below" in p for p in problems)


def test_bench_r03_corruption_signature_trips():
    """~89% of jobs at ~2 iterations with TolX stop reasons — the exact
    BENCH_r03 record shape — must fail the dominance check."""
    its, stops = rec([2] * 45 + [8000] * 5, [TX] * 45 + [MI] * 5)
    problems = _integrity_problems(SolverConfig(), its, stops)
    assert any("implausible from random init" in p for p in problems)


def test_tolx_solvers_exempt_from_dominance():
    """als legitimately TolX-stops in ~14 iterations; the floor must not
    apply to non-class-stop algorithms."""
    its, stops = rec([14, 15, 13], [TX, TX, TX])
    cfg = SolverConfig(algorithm="als")
    assert _integrity_problems(cfg, its, stops) == []


def test_hals_exempt_from_dominance():
    its, stops = rec([22, 20, 24], [TX, TX, TX])
    cfg = SolverConfig(algorithm="hals")
    assert _integrity_problems(cfg, its, stops) == []
    # but an impossible CLASS_STABLE still trips even for hals
    its, stops = rec([22, 20, 24], [CS, TX, TX])
    assert _integrity_problems(cfg, its, stops)


def test_low_maxiter_smoke_run_passes():
    """maxiter below the floor: every job burns to MAX_ITER — legitimate
    for smoke runs, not a corruption signature."""
    its, stops = rec([100, 100, 100], [MI, MI, MI])
    cfg = SolverConfig(max_iter=100)
    assert _integrity_problems(cfg, its, stops) == []


def test_class_stop_disabled_skips_dominance():
    its, stops = rec([40, 44, 38], [TX, TX, TX])
    cfg = SolverConfig(use_class_stop=False)
    assert _integrity_problems(cfg, its, stops) == []


@pytest.mark.parametrize("frac_early,trips", [(0.1, False), (0.5, True)])
def test_dominance_threshold(frac_early, trips):
    n = 20
    ne = int(n * frac_early)
    its, stops = rec([10] * ne + [FLOOR + 98] * (n - ne),
                     [TX] * ne + [CS] * (n - ne))
    problems = _integrity_problems(SolverConfig(), its, stops)
    assert bool(problems) == trips
