"""Out-of-core tile pipeline (ISSUE 17 tentpole): plan determinism,
the in-core bit-identity contract, prefetch inertness, and the cache/
fingerprint key interaction.

The acceptance property: where A fits in-core (one tile), the tiled
sweep is BIT-IDENTICAL to the dense sweep — sweep() delegates a
single-tile dense input back to the in-core path with ``tile_rows``
stripped, so identity is by construction, and these tests pin that the
construction holds per engine family. Multi-tile runs change the Gram
reduction order (f32 accumulation in fixed tile order), so their
contract is prefetch-toggle bit-identity (overlap must never change
math) plus statistical agreement with the dense result. Heavy engine
variants carry the ``slow`` marker; tier-1 keeps the smallest shapes.
"""

import dataclasses

import numpy as np
import pytest

from nmfx import tiles
from nmfx.api import nmfconsensus
from nmfx.config import TILED_ALGORITHMS, SolverConfig

KW = dict(ks=(2, 3), restarts=4, seed=5, use_mesh=False)


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=60, n_per_group=10, seed=7)


@pytest.fixture(autouse=True)
def _tile_globals_restored():
    yield
    tiles.set_tile_budget_bytes(None)
    tiles.set_tile_prefetch(True)


def assert_bit_identical(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        s, q = got.per_k[k], ref.per_k[k]
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            sv = np.ascontiguousarray(np.asarray(getattr(s, field)))
            qv = np.ascontiguousarray(np.asarray(getattr(q, field)))
            assert sv.shape == qv.shape and sv.dtype == qv.dtype \
                and sv.tobytes() == qv.tobytes(), f"{field} k={k}"
        assert s.rho == q.rho, f"rho k={k}"


# ---------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------

def test_plan_boundaries_cover_matrix_exactly():
    plan = tiles.TilePlan(m=100, n=40, tile_rows=30)
    assert plan.n_tiles == 4
    assert plan.boundaries == ((0, 30), (30, 60), (60, 90), (90, 100))
    assert plan.boundaries[-1][1] == plan.m


def test_plan_clamps_tile_rows_to_m():
    plan = tiles.TilePlan(m=10, n=4, tile_rows=64)
    assert plan.tile_rows == 10 and plan.n_tiles == 1


def test_plan_rejects_degenerate():
    with pytest.raises(ValueError, match="degenerate"):
        tiles.TilePlan(m=0, n=4, tile_rows=1)
    with pytest.raises(ValueError, match="tile_rows"):
        tiles.TilePlan(m=4, n=4, tile_rows=0)


def test_resolve_auto_sizes_two_buffers_to_budget():
    # budget fits 2 buffers of 25 rows x 10 cols x 4 bytes
    rows = tiles.resolve_tile_rows("auto", m=200, n=10, itemsize=4,
                                   budget=2 * 25 * 10 * 4)
    assert rows == 25
    assert tiles.resolve_tile_rows(999, m=40, n=10, itemsize=4) == 40
    with pytest.raises(ValueError, match="resolve"):
        tiles.resolve_tile_rows("huge", m=40, n=10, itemsize=4)


def test_budget_override_feeds_plan_for(small_data):
    itemsize = 4  # float32 solve dtype
    n = small_data.shape[1]
    tiles.set_tile_budget_bytes(2 * 16 * n * itemsize)
    scfg = SolverConfig(algorithm="mu", tile_rows="auto")
    plan = tiles.plan_for(small_data, scfg)
    assert plan.tile_rows == 16
    assert plan.n_tiles == -(-small_data.shape[0] // 16)
    # identical inputs -> identical plan (determinism: the plan is part
    # of the checkpoint fingerprint)
    assert tiles.plan_for(small_data, scfg) == plan
    assert plan.as_meta()["n_tiles"] == plan.n_tiles


def test_config_rejects_untileable_combinations():
    with pytest.raises(ValueError, match="tile_rows"):
        SolverConfig(algorithm="als", tile_rows=8)
    with pytest.raises(ValueError, match="tile_rows"):
        SolverConfig(algorithm="mu", backend="pallas", tile_rows=8)
    with pytest.raises(ValueError, match="tile_rows"):
        SolverConfig(algorithm="mu", tile_rows=True)
    assert "als" not in TILED_ALGORITHMS and "kl" not in TILED_ALGORITHMS


# ---------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------

def test_tile_rows_resolves_tiled_family_and_disables_grid():
    from nmfx.sweep import grid_exec_ok, resolve_engine_family

    scfg = SolverConfig(algorithm="mu", tile_rows=8)
    assert resolve_engine_family(scfg, None) == "tiled"
    assert not grid_exec_ok(scfg, None)


def test_base_solve_refuses_tile_rows(small_data):
    from nmfx.solvers import base

    a32 = np.asarray(small_data, np.float32)
    m, n = a32.shape
    rng = np.random.default_rng(0)
    w0 = rng.uniform(0.1, 1.0, (m, 2)).astype(np.float32)
    h0 = rng.uniform(0.1, 1.0, (2, n)).astype(np.float32)
    scfg = SolverConfig(algorithm="mu", max_iter=5, tile_rows=8)
    with pytest.raises(ValueError, match="tile_rows"):
        base.solve(a32, w0, h0, scfg)


# ---------------------------------------------------------------------
# the in-core contract: one tile == dense, bitwise
# ---------------------------------------------------------------------

ENGINES = [
    pytest.param(SolverConfig(algorithm="mu", max_iter=30,
                              backend="packed"), id="mu-packed"),
    pytest.param(SolverConfig(algorithm="hals", max_iter=30),
                 id="hals"),
]

ENGINES_SLOW = [
    pytest.param(SolverConfig(algorithm="mu", max_iter=30,
                              backend="vmap"), id="mu-vmap"),
]


def _delegation_roundtrip(small_data, scfg):
    ref = nmfconsensus(small_data, solver_cfg=scfg, **KW)
    one_tile = dataclasses.replace(scfg,
                                   tile_rows=small_data.shape[0])
    got = nmfconsensus(small_data, solver_cfg=one_tile, **KW)
    assert_bit_identical(got, ref)


@pytest.mark.parametrize("scfg", ENGINES)
def test_single_tile_delegates_bit_identical(small_data, scfg):
    _delegation_roundtrip(small_data, scfg)


@pytest.mark.slow
@pytest.mark.parametrize("scfg", ENGINES_SLOW)
def test_single_tile_delegates_bit_identical_slow(small_data, scfg):
    _delegation_roundtrip(small_data, scfg)


# ---------------------------------------------------------------------
# multi-tile: prefetch inertness + dense agreement
# ---------------------------------------------------------------------

@pytest.mark.parametrize("algorithm", sorted(TILED_ALGORITHMS))
def test_prefetch_toggle_is_bit_inert(small_data, algorithm):
    """Double-buffered streaming reorders TRANSFERS, never math: the
    multi-tile sweep with prefetch off must match prefetch on bitwise."""
    scfg = SolverConfig(algorithm=algorithm, max_iter=30, tile_rows=16)
    on = nmfconsensus(small_data, solver_cfg=scfg, **KW)
    tiles.set_tile_prefetch(False)
    off = nmfconsensus(small_data, solver_cfg=scfg, **KW)
    tiles.set_tile_prefetch(True)
    assert_bit_identical(on, off)


def test_multi_tile_agrees_with_dense(small_data):
    """Multi-tile Gram accumulation is a different f32 summation order,
    so the dense contract is agreement, not bit-identity."""
    from nmfx.agreement import consensus_agreement

    scfg = SolverConfig(algorithm="mu", max_iter=200)
    dense = nmfconsensus(small_data, solver_cfg=scfg, **KW)
    tiled = nmfconsensus(
        small_data,
        solver_cfg=dataclasses.replace(scfg, tile_rows=16), **KW)
    rep = consensus_agreement(tiled, dense)
    assert rep["min_ari"] >= 0.9
    assert rep["max_rho_gap"] <= 0.1


def test_multi_tile_books_stream_counters(small_data):
    passes0 = tiles._tile_passes_total.value()
    h2d0 = tiles._tile_h2d_bytes_total.value()
    scfg = SolverConfig(algorithm="mu", max_iter=20, tile_rows=16)
    nmfconsensus(small_data, solver_cfg=scfg, **KW)
    assert tiles._tile_passes_total.value() > passes0
    assert tiles._tile_h2d_bytes_total.value() > h2d0


# ---------------------------------------------------------------------
# cache/fingerprint key interaction (ISSUE 17 satellite): tile_rows is
# a numerics-affecting field and must reach every identity layer
# ---------------------------------------------------------------------

def test_tile_rows_in_exec_and_persist_keys():
    from nmfx.exec_cache import persist_key_fields, solver_key_fields

    assert "tile_rows" in solver_key_fields()
    assert "tile_rows" in persist_key_fields()
    # two configs differing only in tile_rows must never alias one
    # cached executable (in-memory key = dataclass hash/eq) nor one
    # disk entry (persistent key = dataclass repr)
    a = SolverConfig(algorithm="mu", tile_rows=8)
    b = SolverConfig(algorithm="mu", tile_rows=16)
    assert a != b and hash(a) != hash(b)
    assert repr(a) != repr(b)


def test_tile_rows_in_registry_fingerprint_fields():
    from nmfx.registry import fingerprint_solver_fields

    assert "tile_rows" in fingerprint_solver_fields()


def test_nmfx001_live_universe_covers_tile_rows():
    """Clean twin: the real config/exec-cache/registry triple passes
    NMFX001 with tile_rows present everywhere."""
    from nmfx.analysis.rules_config import (_live_universe,
                                            check_config_coverage)

    universe = _live_universe()
    assert "tile_rows" in universe["solver_fields"]
    assert check_config_coverage(**universe) == []


def test_nmfx001_fires_if_tile_rows_leaves_bucket_key():
    """Bad universe: dropping tile_rows from the exec-cache bucket key
    (what a compare=False regression would do) must fire NMFX001 —
    a tiled and an in-core config would otherwise share an executable."""
    from nmfx.analysis.rules_config import (_live_universe,
                                            check_config_coverage)

    universe = _live_universe()
    universe["exec_key_covered"] = frozenset(
        universe["exec_key_covered"]) - {"tile_rows"}
    problems = check_config_coverage(**universe)
    assert any("tile_rows" in p and "bucket key" in p for p in problems)


def test_nmfx001_fires_if_tile_rows_leaves_persist_key():
    from nmfx.analysis.rules_config import (_live_universe,
                                            check_config_coverage)

    universe = _live_universe()
    universe["persist_key_covered"] = frozenset(
        universe["persist_key_covered"]) - {"tile_rows"}
    problems = check_config_coverage(**universe)
    assert any("tile_rows" in p and "persistent" in p for p in problems)


def test_checkpoint_fingerprint_embeds_tile_plan(small_data):
    """Two tiled runs with different plans must cold-start each other's
    ledgers: the fingerprint hashes the resolved TilePlan meta."""
    from nmfx.checkpoint import _fingerprint
    from nmfx.config import ConsensusConfig, InitConfig

    ccfg = ConsensusConfig(ks=(2, 3), restarts=4, seed=5)
    icfg = InitConfig()
    a32 = np.asarray(small_data, np.float32)
    fp8 = _fingerprint(a32, ccfg,
                       SolverConfig(algorithm="mu", tile_rows=8), icfg)
    fp16 = _fingerprint(a32, ccfg,
                        SolverConfig(algorithm="mu", tile_rows=16),
                        icfg)
    fp_dense = _fingerprint(a32, ccfg, SolverConfig(algorithm="mu"),
                            icfg)
    assert fp8 != fp16
    assert fp8 != fp_dense and fp16 != fp_dense
