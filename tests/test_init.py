"""Initialization (nmfx/init.py) unit tests: random ranges/reproducibility
and the NNDSVD scheme against a direct NumPy construction of the reference
algorithm (libnmf/generatematrix.c:145-247), plus neals robustness on
singular Grams (the case the reference handles with a lazy QR fallback,
libnmf/nmf_neals.c:206-291; here a Tikhonov-jittered Cholesky)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import InitConfig, SolverConfig
from nmfx.init import initialize, nndsvd_init, random_init
from nmfx.solvers.base import StopReason, residual_norm, solve


def test_random_init_range_and_reproducibility():
    cfg = InitConfig(minval=0.25, maxval=0.75)
    w1, h1 = random_init(jax.random.key(4), 50, 20, 3, cfg)
    w2, h2 = random_init(jax.random.key(4), 50, 20, 3, cfg)
    w3, _ = random_init(jax.random.key(5), 50, 20, 3, cfg)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
    assert not np.array_equal(np.asarray(w1), np.asarray(w3))
    for arr, shape in ((w1, (50, 3)), (h1, (3, 20))):
        a = np.asarray(arr)
        assert a.shape == shape
        assert a.min() >= 0.25 and a.max() < 0.75


def _nndsvd_numpy(a: np.ndarray, k: int) -> tuple[np.ndarray, np.ndarray]:
    """Direct NumPy transliteration of Boutsidis NNDSVD as the reference
    implements it (generatematrix.c:172-247): leading pair from |u0|,|v0|;
    later pairs keep the dominant sign-split side scaled by sqrt(s*term)."""
    u, s, vt = np.linalg.svd(a, full_matrices=False)
    u, s, vt = u[:, :k], s[:k], vt[:k, :]
    m, n = a.shape
    w = np.zeros((m, k))
    h = np.zeros((k, n))
    w[:, 0] = np.sqrt(s[0]) * np.abs(u[:, 0])
    h[0, :] = np.sqrt(s[0]) * np.abs(vt[0, :])
    for j in range(1, k):
        uj, vj = u[:, j], vt[j, :]
        up, un = np.maximum(uj, 0), np.maximum(-uj, 0)
        vp, vn = np.maximum(vj, 0), np.maximum(-vj, 0)
        nup, nun = np.linalg.norm(up), np.linalg.norm(un)
        nvp, nvn = np.linalg.norm(vp), np.linalg.norm(vn)
        if nup * nvp >= nun * nvn:
            term = nup * nvp
            wj, hj = up / max(nup, 1e-30), vp / max(nvp, 1e-30)
        else:
            term = nun * nvn
            wj, hj = un / max(nun, 1e-30), vn / max(nvn, 1e-30)
        w[:, j] = np.sqrt(s[j] * term) * wj
        h[j, :] = np.sqrt(s[j] * term) * hj
    return w, h


@pytest.mark.parametrize("k", [2, 4])
def test_nndsvd_matches_numpy_reference(k):
    rng = np.random.default_rng(9)
    a = rng.uniform(0.0, 2.0, (40, 18))
    w_ref, h_ref = _nndsvd_numpy(a, k)
    w, h = nndsvd_init(jnp.asarray(a, jnp.float32), k)
    # SVD sign/column conventions can differ only where singular values are
    # degenerate; this fixture has well-separated spectrum
    np.testing.assert_allclose(np.asarray(w), w_ref, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(h), h_ref, rtol=2e-4, atol=2e-4)


def test_nndsvd_nonneg_deterministic_and_better_than_random(low_rank_data):
    a, k = low_rank_data
    a = jnp.asarray(a, jnp.float32)
    w1, h1 = nndsvd_init(a, k)
    w2, h2 = nndsvd_init(a, k)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    assert (np.asarray(w1) >= 0).all() and (np.asarray(h1) >= 0).all()
    # NNDSVD should start much closer to A than a random init on low-rank A
    wr, hr = random_init(jax.random.key(0), *a.shape, k)
    assert float(residual_norm(a, w1, h1)) < 0.5 * float(
        residual_norm(a, wr, hr))


def test_initialize_dispatch(low_rank_data):
    a, k = low_rank_data
    a = jnp.asarray(a, jnp.float32)
    w, h = initialize(jax.random.key(0), a, k, InitConfig(method="nndsvd"),
                      jnp.float32)
    w2, _ = nndsvd_init(a, k)
    np.testing.assert_array_equal(np.asarray(w), np.asarray(w2))
    assert h.shape == (k, a.shape[1])


def test_neals_singular_gram_fallback():
    """Rank-deficient W (duplicate columns) makes WᵀW singular — the case
    the reference meets with its lazy QR switch (nmf_neals.c:206-291) and
    nmfx with the jittered Cholesky: the solve must produce finite factors
    and still reduce the residual."""
    rng = np.random.default_rng(1)
    m, n, k = 40, 15, 3
    a = jnp.asarray(rng.uniform(0.5, 1.5, (m, k)) @
                    rng.uniform(0.5, 1.5, (k, n)), jnp.float32)
    col = rng.uniform(0.1, 1.0, (m, 1))
    w0 = jnp.asarray(np.concatenate([col] * k, axis=1), jnp.float32)  # rank 1
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, (k, n)), jnp.float32)
    cfg = SolverConfig(algorithm="neals", max_iter=60)
    res = solve(a, w0, h0, cfg)
    w, h = np.asarray(res.w), np.asarray(res.h)
    assert np.isfinite(w).all() and np.isfinite(h).all()
    assert (w >= 0).all() and (h >= 0).all()
    assert float(res.dnorm) < float(residual_norm(a, w0, h0))
    assert int(res.stop_reason) in (StopReason.MAX_ITER, StopReason.TOL_X,
                                    StopReason.TOL_FUN)


def test_lanczos_svd_matches_dense():
    from nmfx.ops.lanczos_svd import truncated_svd

    rng = np.random.default_rng(11)
    for m, n in ((80, 30), (30, 80)):
        a = jnp.asarray(rng.uniform(0.0, 2.0, (m, n)), jnp.float32)
        u, s, vt = truncated_svd(a, 4)
        ud, sd, vtd = np.linalg.svd(np.asarray(a, np.float64))
        np.testing.assert_allclose(np.asarray(s), sd[:4], rtol=1e-3)
        # vectors match up to sign
        for j in range(4):
            dot_u = abs(np.dot(np.asarray(u[:, j]), ud[:, j]))
            dot_v = abs(np.dot(np.asarray(vt[j]), vtd[j]))
            assert dot_u > 0.999, (j, dot_u)
            assert dot_v > 0.999, (j, dot_v)
        # reconstruction quality equals the dense rank-4 truncation
        rec = np.asarray(u) * np.asarray(s) @ np.asarray(vt)
        rec_d = (ud[:, :4] * sd[:4]) @ vtd[:4]
        assert np.linalg.norm(rec - rec_d) <= 1e-2 * np.linalg.norm(rec_d)


def test_nndsvd_lanczos_matches_dense(low_rank_data):
    a, k = low_rank_data
    a = jnp.asarray(a, jnp.float32)
    w_d, h_d = nndsvd_init(a, k, svd_method="dense")
    w_l, h_l = nndsvd_init(a, k, svd_method="lanczos")
    np.testing.assert_allclose(np.asarray(w_l), np.asarray(w_d),
                               rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(np.asarray(h_l), np.asarray(h_d),
                               rtol=5e-3, atol=5e-3)


def test_init_config_svd_validation():
    with pytest.raises(ValueError, match="svd_method"):
        InitConfig(svd_method="arpack")


def test_lanczos_svd_degenerate_spectrum_falls_back():
    """Repeated singular values: single-vector Lanczos holds one Ritz copy
    per distinct eigenvalue; the residual guard must detect the missing
    multiplet copy and fall back to the dense factorization."""
    from nmfx.ops.lanczos_svd import truncated_svd

    rng = np.random.default_rng(21)
    q1, _ = np.linalg.qr(rng.normal(size=(60, 4)))
    q2, _ = np.linalg.qr(rng.normal(size=(40, 4)))
    a = jnp.asarray((q1 * np.array([5.0, 5.0, 3.0, 1.0])) @ q2.T,
                    jnp.float32)
    _, s, _ = truncated_svd(a, 4)
    np.testing.assert_allclose(np.asarray(s), [5.0, 5.0, 3.0, 1.0],
                               rtol=1e-3, atol=1e-3)


def test_nndsvd_bad_svd_method_rejected(low_rank_data):
    a, k = low_rank_data
    with pytest.raises(ValueError, match="svd_method"):
        nndsvd_init(jnp.asarray(a, jnp.float32), k, svd_method="Lanczos")
