"""Durable sweeps (ISSUE 9 tentpole): checkpoint/resume ledger,
preemption tolerance, and the bit-identical resume contract.

The acceptance property: killing a checkpointed sweep mid-run and
resuming it produces a ``ConsensusResult`` BIT-IDENTICAL to an
uninterrupted checkpointed run of the same (data, config, chunk plan) —
consensus, rho, membership, order, iterations, stop_reasons, dnorms,
best_w/best_h — on every engine family the chunk executor routes
(packed mu, vmapped mu, and the non-mu vmapped family). The injected
kill is the ``proc.preempt`` fault site, which fires between a chunk's
solve and its commit — the worst realistic kill point (the in-flight
chunk is lost, committed records survive). Heavy engine variants carry
the ``slow`` marker; tier-1 keeps the smallest shapes.
"""

import os

import numpy as np
import pytest

from nmfx import checkpoint as ckpt
from nmfx import faults
from nmfx.api import nmfconsensus
from nmfx.config import (CheckpointConfig, ConsensusConfig, InitConfig,
                         SolverConfig)

KW = dict(ks=(2, 3), restarts=4, seed=5)


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=60, n_per_group=10, seed=7)


@pytest.fixture(autouse=True)
def _fresh_warnings():
    faults._reset_warned()
    yield
    faults.disarm()


def _cfg(path, chunk=2, **kw):
    return CheckpointConfig(directory=str(path), every_n_restarts=chunk,
                            **kw)


def assert_bit_identical(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        s, q = got.per_k[k], ref.per_k[k]
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            sv = np.ascontiguousarray(np.asarray(getattr(s, field)))
            qv = np.ascontiguousarray(np.asarray(getattr(q, field)))
            assert sv.shape == qv.shape and sv.dtype == qv.dtype \
                and sv.tobytes() == qv.tobytes(), f"{field} k={k}"
        assert s.rho == q.rho, f"rho k={k}"


def _run(data, path, scfg=None, chunk=2, **over):
    kw = dict(KW, **over)
    return nmfconsensus(data, solver_cfg=scfg, max_iter=None,
                        checkpoint=_cfg(path, chunk=chunk), **kw)


# ---------------------------------------------------------------------
# plan + config basics
# ---------------------------------------------------------------------

def test_plan_chunks_deterministic_boundaries():
    assert ckpt.plan_chunks(10, 4) == ((0, 4), (4, 8), (8, 10))
    assert ckpt.plan_chunks(4, None) == ((0, 4),)
    assert ckpt.plan_chunks(3, 8) == ((0, 3),)


def test_checkpoint_config_validation(tmp_path):
    with pytest.raises(ValueError, match="every_n_restarts"):
        CheckpointConfig(str(tmp_path), every_n_restarts=0)
    with pytest.raises(ValueError, match="every_s"):
        CheckpointConfig(str(tmp_path), every_s=0.0)
    with pytest.raises(ValueError, match="directory"):
        CheckpointConfig(directory="")


def test_compose_guards(small_data, tmp_path):
    from nmfx.sweep import default_mesh

    with pytest.raises(ValueError, match="not both"):
        nmfconsensus(small_data, checkpoint=str(tmp_path / "a"),
                     checkpoint_dir=str(tmp_path / "b"), **KW)
    with pytest.raises(ValueError, match="keep_factors"):
        nmfconsensus(small_data, checkpoint=str(tmp_path / "a"),
                     keep_factors=True, **KW)
    mesh = default_mesh()
    if mesh is not None:
        with pytest.raises(ValueError, match="mesh"):
            nmfconsensus(small_data, checkpoint=str(tmp_path / "a"),
                         mesh=mesh, **KW)


# ---------------------------------------------------------------------
# resume semantics
# ---------------------------------------------------------------------

def test_fully_checkpointed_rerun_bit_identical(small_data, tmp_path):
    """A fully-checkpointed re-run is bit-identical AND solves nothing
    (counter-gated, the exec-cache discipline)."""
    scfg = SolverConfig(algorithm="mu", max_iter=40)
    r1 = _run(small_data, tmp_path / "c", scfg)
    solved = ckpt.chunks_solved_count()
    r2 = _run(small_data, tmp_path / "c", scfg)
    assert ckpt.chunks_solved_count() == solved  # zero re-solves
    assert_bit_identical(r2, r1)


@pytest.mark.slow
def test_checkpointed_close_to_plain_sweep(small_data, tmp_path):
    """A checkpointed run agrees with the plain sweep to float
    tolerance (different consensus reduction arithmetic: exact host
    integer counts vs on-device f32 einsum)."""
    scfg = SolverConfig(algorithm="mu", max_iter=40)
    r1 = _run(small_data, tmp_path / "c", scfg)
    plain = nmfconsensus(small_data, solver_cfg=scfg, use_mesh=False,
                         **KW)
    for k in KW["ks"]:
        np.testing.assert_allclose(plain.per_k[k].consensus,
                                   r1.per_k[k].consensus, atol=1e-5)


@pytest.mark.slow
def test_widening_ks_reuses_completed_ranks(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=40)
    r1 = _run(small_data, tmp_path / "c", scfg, ks=(2,))
    solved = ckpt.chunks_solved_count()
    r2 = _run(small_data, tmp_path / "c", scfg, ks=(2, 3))
    # rank 2's chunks loaded, only rank 3's solved (2 chunks of 2)
    assert ckpt.chunks_solved_count() == solved + 2
    assert np.asarray(r1.per_k[2].consensus).tobytes() == \
        np.asarray(r2.per_k[2].consensus).tobytes()


#: tier-1 keeps ONE engine representative (packed mu — the default
#: family); the other chunk-executor routes ride the slow tier to
#: respect the ~870 s budget (tests/conftest discipline from PR 2)
ENGINES = [
    pytest.param(SolverConfig(algorithm="mu", max_iter=30),
                 id="mu-packed"),
]

ENGINES_SLOW = [
    pytest.param(SolverConfig(algorithm="mu", max_iter=30,
                              backend="vmap"), id="mu-vmap"),
    pytest.param(SolverConfig(algorithm="hals", max_iter=30),
                 id="hals-grid-family"),
    pytest.param(SolverConfig(algorithm="als", max_iter=30), id="als"),
    pytest.param(SolverConfig(algorithm="kl", max_iter=30), id="kl"),
]


def _kill_resume_roundtrip(small_data, tmp_path, scfg):
    """Reference uninterrupted run, killed-at-~50% run (proc.preempt),
    resume, bit-compare — the acceptance criterion's body."""
    ref = _run(small_data, tmp_path / "ref", scfg)
    faults.arm("proc.preempt", every=3, max_fires=1)  # ~50% of 4 chunks
    try:
        with pytest.raises(ckpt.Preempted):
            _run(small_data, tmp_path / "kill", scfg)
    finally:
        faults.disarm("proc.preempt")
    persisted = [n for n in os.listdir(tmp_path / "kill")
                 if n.endswith(".npz")]
    assert 0 < len(persisted) < 4  # really mid-run: partial ledger
    res = _run(small_data, tmp_path / "kill", scfg)
    assert_bit_identical(res, ref)


@pytest.mark.parametrize("scfg", ENGINES)
def test_kill_at_half_then_resume_bit_identical(small_data, tmp_path,
                                                scfg):
    _kill_resume_roundtrip(small_data, tmp_path, scfg)


@pytest.mark.slow
@pytest.mark.parametrize("scfg", ENGINES_SLOW)
def test_kill_resume_bit_identical_slow_engines(small_data, tmp_path,
                                                scfg):
    _kill_resume_roundtrip(small_data, tmp_path, scfg)


@pytest.mark.slow
def test_grid_exec_knobs_inert_under_checkpointing(small_data,
                                                   tmp_path):
    """grid_exec/grid_slots are execution strategy the chunk plan
    replaces: runs differing only in them share one ledger (manifest
    unchanged — CHECKPOINT_EXEMPT_FIELDS) and stay bit-identical."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    r1 = _run(small_data, tmp_path / "c", scfg, grid_exec="grid")
    solved = ckpt.chunks_solved_count()
    r2 = _run(small_data, tmp_path / "c", scfg, grid_exec="per_k",
              grid_slots=16)
    assert ckpt.chunks_solved_count() == solved  # same manifest: resume
    assert_bit_identical(r2, r1)


# ---------------------------------------------------------------------
# manifest guard: never a wrong resume, never a crash
# ---------------------------------------------------------------------

def test_manifest_mismatch_is_clean_cold_start(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "c", scfg, seed=5)
    with pytest.warns(RuntimeWarning, match="COLD START"):
        r_new = _run(small_data, tmp_path / "c", scfg, seed=6)
    ref = _run(small_data, tmp_path / "fresh", scfg, seed=6)
    assert_bit_identical(r_new, ref)  # never the stale seed's numbers


@pytest.mark.slow
def test_manifest_covers_solver_numerics(small_data, tmp_path):
    """A numerics-affecting SolverConfig change cold-starts; the
    declared non-numerics knob (restart_chunk) resumes."""
    _run(small_data, tmp_path / "c",
         SolverConfig(algorithm="mu", max_iter=30))
    with pytest.warns(RuntimeWarning, match="COLD START"):
        _run(small_data, tmp_path / "c",
             SolverConfig(algorithm="mu", max_iter=30, tol_x=1e-6))
    faults._reset_warned()
    solved = ckpt.chunks_solved_count()
    _run(small_data, tmp_path / "c",
         SolverConfig(algorithm="mu", max_iter=30, tol_x=1e-6,
                      restart_chunk=2))
    assert ckpt.chunks_solved_count() == solved  # resumed, no warning


@pytest.mark.slow
def test_chunk_plan_change_is_cold_start(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "c", scfg, chunk=2)
    with pytest.warns(RuntimeWarning, match="COLD START"):
        r = _run(small_data, tmp_path / "c", scfg, chunk=4)
    ref = _run(small_data, tmp_path / "f", scfg, chunk=4)
    assert_bit_identical(r, ref)


@pytest.mark.slow
def test_resume_false_recomputes(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    r1 = _run(small_data, tmp_path / "c", scfg)
    solved = ckpt.chunks_solved_count()
    with pytest.warns(RuntimeWarning, match="resume=False"):
        r2 = nmfconsensus(small_data, solver_cfg=scfg,
                          checkpoint=_cfg(tmp_path / "c", resume=False),
                          **KW)
    assert ckpt.chunks_solved_count() == solved + 4
    assert_bit_identical(r2, r1)  # recompute, same numbers


def test_torn_record_skipped_and_rerun(small_data, tmp_path):
    """A truncated record (the crash class predating atomic writes,
    or external corruption) is skipped warn-once and its chunk re-runs
    — bit-identical result, never a crash."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    ref = _run(small_data, tmp_path / "c", scfg)
    with open(tmp_path / "c" / "k2_r0-2.npz", "r+b") as fh:
        fh.truncate(32)
    with pytest.warns(RuntimeWarning, match="torn/corrupt"):
        res = _run(small_data, tmp_path / "c", scfg)
    assert_bit_identical(res, ref)


def test_keep_factors_refused(small_data, tmp_path):
    with pytest.raises(ValueError, match="keep_factors"):
        _run(small_data, tmp_path / "c", keep_factors=True)


# ---------------------------------------------------------------------
# chaos sites + buffered (every_s) persistence
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_ckpt_write_fault_degrades_not_crashes(small_data, tmp_path):
    """An armed ckpt.write fault (disk-full rehearsal) costs durability
    only: the run completes warn-once with identical results and an
    empty ledger; the next (unarmed) run recomputes."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    ref = _run(small_data, tmp_path / "ref", scfg)
    faults.arm("ckpt.write", every=1)
    try:
        with pytest.warns(RuntimeWarning, match="persist"):
            res = _run(small_data, tmp_path / "c", scfg)
    finally:
        faults.disarm("ckpt.write")
    assert_bit_identical(res, ref)
    assert not [n for n in os.listdir(tmp_path / "c")
                if n.endswith(".npz")]


@pytest.mark.slow
def test_ckpt_load_fault_forces_recompute_exact(small_data, tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    ref = _run(small_data, tmp_path / "c", scfg)
    solved = ckpt.chunks_solved_count()
    faults.arm("ckpt.load", every=1)
    try:
        with pytest.warns(RuntimeWarning, match="torn/corrupt"):
            res = _run(small_data, tmp_path / "c", scfg)
    finally:
        faults.disarm("ckpt.load")
    assert ckpt.chunks_solved_count() == solved + 4  # all re-ran
    assert_bit_identical(res, ref)


def _dummy_record(m=3, n=4, k=2, c=2):
    from nmfx.sweep import ChunkSweepOutput

    return ChunkSweepOutput(
        labels=np.zeros((c, n), np.int32),
        iterations=np.zeros((c,), np.int32),
        dnorms=np.zeros((c,), np.float32),
        stop_reasons=np.zeros((c,), np.int32),
        best_local=np.int32(0),
        best_w=np.zeros((m, k), np.float32),
        best_h=np.zeros((k, n), np.float32))


def _open_buffered(tmp_path, every_s=3600.0):
    ccfg = ConsensusConfig(ks=(2,), restarts=4, seed=0)
    scfg = SolverConfig(algorithm="mu", max_iter=10)
    a = np.ones((3, 4), np.float32)
    cp = CheckpointConfig(str(tmp_path / "buf"), every_n_restarts=2,
                          every_s=every_s)
    return ckpt.SweepCheckpoint.open(a, ccfg, scfg, InitConfig(), cp)


def test_every_s_buffers_until_flush(tmp_path):
    ck = _open_buffered(tmp_path)
    ck.save(2, 0, 2, _dummy_record())
    assert not ck.has(2, 0, 2)  # buffered, not yet durable
    ck.flush()
    assert ck.has(2, 0, 2)
    assert ck.try_load(2, 0, 2) is not None


def test_signal_flush_hook_flushes_then_defers(tmp_path):
    """The SIGTERM flush hook writes the buffered tail before the
    process dies, then re-raises the default disposition — the
    graceful-preemption guarantee every_s durability rests on."""
    import signal

    ck = _open_buffered(tmp_path)
    restore = ckpt.install_signal_flush(ck)
    try:
        ck.save(2, 0, 2, _dummy_record())
        assert not ck.has(2, 0, 2)
        handler = signal.getsignal(signal.SIGTERM)
        with pytest.raises(SystemExit) as exc:
            handler(signal.SIGTERM, None)
        assert exc.value.code == 128 + signal.SIGTERM
        assert ck.has(2, 0, 2)  # flushed before dying
    finally:
        restore()
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


@pytest.mark.slow
def test_quarantine_composes_with_checkpointing(small_data, tmp_path):
    """A solve.nonfinite-poisoned lane is quarantined inside the chunk
    executor (trace_token keys the builder cache) and the record
    carries NUMERIC_FAULT; the survivor consensus finalizes exactly."""
    from nmfx.solvers.base import StopReason

    scfg = SolverConfig(algorithm="mu", max_iter=30)
    faults.arm("solve.nonfinite", lanes=((2, 1),))
    try:
        res = _run(small_data, tmp_path / "c", scfg)
    finally:
        faults.disarm("solve.nonfinite")
    stops = np.asarray(res.per_k[2].stop_reasons)
    assert stops[1] == int(StopReason.NUMERIC_FAULT)
    assert (stops != int(StopReason.NUMERIC_FAULT)).sum() == 3
    assert np.isfinite(res.per_k[2].consensus).all()


@pytest.mark.slow
def test_cold_start_spares_foreign_files(small_data, tmp_path):
    """A cold start clears ONLY the ledger's own completion records —
    user files, serve spill records, and a legacy SweepRegistry's
    per-rank k<k>.npz parked in the same directory survive."""
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    _run(small_data, tmp_path / "c", scfg, seed=5)
    (tmp_path / "c" / "notes.txt").write_text("keep me")
    (tmp_path / "c" / "k2.npz").write_bytes(b"legacy registry record")
    (tmp_path / "c" / "spill_1_0.npz").write_bytes(b"serve spill")
    with pytest.warns(RuntimeWarning, match="COLD START"):
        _run(small_data, tmp_path / "c", scfg, seed=6)
    for name in ("notes.txt", "k2.npz", "spill_1_0.npz"):
        assert (tmp_path / "c" / name).exists(), name


@pytest.mark.slow
def test_legacy_registry_dir_warns_not_resumes(small_data, tmp_path):
    """Pointing the durable ledger at a legacy SweepRegistry directory
    warns that its records are a different format (left untouched)
    instead of silently recomputing next to them."""
    nmfconsensus(small_data, max_iter=30, use_mesh=False,
                 checkpoint_dir=str(tmp_path / "c"), **KW)
    assert (tmp_path / "c" / "registry.json").exists()
    with pytest.warns(RuntimeWarning, match="legacy per-rank"):
        _run(small_data, tmp_path / "c",
             SolverConfig(algorithm="mu", max_iter=30))
    assert (tmp_path / "c" / "k2.npz").exists()  # untouched


def test_close_never_spills_cancelled_requests(tmp_path):
    """A future the caller cancelled before shutdown is not spilled:
    readmit() must not resurrect explicitly-cancelled work."""
    from nmfx.serve import NMFXServer, ServeConfig

    spill = str(tmp_path / "spill")
    srv = NMFXServer(ServeConfig(spill_dir=spill), start=False)
    f1 = srv.submit(np.abs(np.random.default_rng(0).random((8, 6))),
                    ks=(2,), restarts=2)
    assert f1.cancel()
    srv.close(cancel_pending=True)
    assert srv.counters["spilled"] == 0
    import os

    assert not os.path.isdir(spill) or os.listdir(spill) == []


# ---------------------------------------------------------------------
# out-of-core (tiled) chunks: mid-matrix partials (ISSUE 17)
# ---------------------------------------------------------------------

@pytest.mark.parametrize("algorithm", ["mu", "hals"])
def test_tiled_kill_mid_matrix_then_resume_bit_identical(
        small_data, tmp_path, algorithm):
    """The atlas-scale acceptance property: preempting a TILED
    checkpointed sweep mid-chunk leaves a fingerprint-stamped partial
    record (``k*_r*-*.part.npz``) on disk, the resumed run CONSUMES it
    (rather than recomputing the chunk from scratch) and still lands
    bit-identical to an uninterrupted run — and commits clear the
    partial. The tiled chunk executor polls ``proc.preempt`` at every
    convergence-check boundary AFTER saving the partial, so the
    injected kill is a genuine mid-matrix preemption."""
    from nmfx import tiles

    scfg = SolverConfig(algorithm=algorithm, max_iter=60, tile_rows=16)
    ref = _run(small_data, tmp_path / "ref", scfg)
    faults.arm("proc.preempt", every=3, max_fires=1)
    try:
        with pytest.raises(ckpt.Preempted):
            _run(small_data, tmp_path / "kill", scfg)
    finally:
        faults.disarm("proc.preempt")
    parts = [n for n in os.listdir(tmp_path / "kill")
             if n.endswith(".part.npz")]
    assert parts, "the in-flight chunk's partial must survive the kill"
    before = tiles._tile_partial_resumes_total.value()
    res = _run(small_data, tmp_path / "kill", scfg)
    assert tiles._tile_partial_resumes_total.value() - before >= 1, \
        "the surviving partial was recomputed, not resumed"
    assert not [n for n in os.listdir(tmp_path / "kill")
                if n.endswith(".part.npz")], \
        "partials must be cleared once their chunk commits"
    assert_bit_identical(res, ref)


def test_tiled_uninterrupted_run_leaves_no_partials(small_data,
                                                    tmp_path):
    scfg = SolverConfig(algorithm="mu", max_iter=40, tile_rows=16)
    _run(small_data, tmp_path / "c", scfg)
    names = os.listdir(tmp_path / "c")
    assert not [n for n in names if n.endswith(".part.npz")]
    assert any(n.endswith(".npz") for n in names)  # committed records


def test_tiled_plan_change_is_cold_start(small_data, tmp_path):
    """A different tile plan is a different reduction order: the
    manifest must not resume across tile_rows changes."""
    scfg16 = SolverConfig(algorithm="mu", max_iter=30, tile_rows=16)
    _run(small_data, tmp_path / "c", scfg16)
    before = ckpt.chunks_solved_count()
    scfg8 = SolverConfig(algorithm="mu", max_iter=30, tile_rows=8)
    with pytest.warns(RuntimeWarning, match="cold"):
        _run(small_data, tmp_path / "c", scfg8)
    assert ckpt.chunks_solved_count() - before == 4  # all recomputed


def test_sparse_checkpointed_sweep_resumes(tmp_path):
    """Sparse inputs route through the tiled chunk executor and the
    durable ledger: a second run of the same (sparse data, config)
    serves every chunk from disk."""
    from nmfx.datasets import make_sparse_design

    sp = make_sparse_design(80, 24, k=2, density=0.3, seed=6)
    scfg = SolverConfig(algorithm="mu", max_iter=30)
    ref = _run(sp, tmp_path / "c", scfg)
    before = ckpt.chunks_solved_count()
    again = _run(sp, tmp_path / "c", scfg)
    assert ckpt.chunks_solved_count() == before  # zero new solves
    assert_bit_identical(again, ref)
