"""Meshed-vs-unmeshed parity matrix (ISSUE 19, satellite c).

The mesh tier's core promise is that placement is a *pricing* decision,
never a *numerics* decision: a sweep on a restart-sharded mesh must be
BIT-identical to the single-device sweep (same keys, same math, only
device placement differs), and a grid (feature×sample) mesh — whose
per-iteration psums reorder float reductions — must still agree on the
consensus matrix to clustering tolerance. Every grid-driver engine plus
the packed-mu engine goes through the matrix on 4 of the 8 forced CPU
devices (conftest.py pins the platform). The heavy engines ride the
``slow`` marker; ``kl`` and ``mu`` (the two serving defaults) stay in
tier-1 so the contract is checked on every push.
"""

import numpy as np
import pytest

from nmfx.config import ConsensusConfig, SolverConfig
from nmfx.sweep import GRID_SOLVERS, grid_mesh, sweep

# engines cheap enough for tier-1; the rest of the matrix is `slow`
_FAST = ("kl", "mu")
_ENGINES = tuple(sorted(set(GRID_SOLVERS) | {"mu"}))
_BIT_FIELDS = ("consensus", "labels", "dnorms")


def _params():
    return [
        pytest.param(alg, marks=() if alg in _FAST else (pytest.mark.slow,))
        for alg in _ENGINES
    ]


def _run(a, alg, mesh, restarts=6):
    scfg = SolverConfig(algorithm=alg, max_iter=60)
    ccfg = ConsensusConfig(ks=(3,), restarts=restarts, seed=123)
    return sweep(a, ccfg, scfg, mesh=mesh)[3]


@pytest.mark.parametrize("alg", _params())
def test_restart_mesh_bit_identical(two_group_data, alg):
    ref = _run(two_group_data, alg, mesh=None)
    got = _run(two_group_data, alg, mesh=grid_mesh(4, 1, 1))
    for field in _BIT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, field)),
            np.asarray(getattr(got, field)),
            err_msg=f"{alg}: {field} diverged on the restart mesh")


@pytest.mark.parametrize("alg", _params())
def test_grid_mesh_agreement(two_group_data, alg):
    """Feature×sample sharding reorders the psum reductions, so the gate
    is agreement (consensus entries within clustering tolerance), not
    bit-identity."""
    ref = _run(two_group_data, alg, mesh=None)
    got = _run(two_group_data, alg, mesh=grid_mesh(1, 2, 2))
    assert np.allclose(np.asarray(ref.consensus),
                       np.asarray(got.consensus), atol=0.35), (
        f"{alg}: grid-mesh consensus diverged beyond tolerance")


def test_restart_mesh_pads_surplus_lanes(two_group_data):
    """5 restarts on 4 shards pads to 8 lanes; the 3 surplus lanes are
    computed-and-discarded, booked on the honesty counter, and the
    result is still bit-identical to the unmeshed sweep."""
    from nmfx.obs import metrics as obs_metrics

    def pads():
        rec = obs_metrics.registry().snapshot().get(
            "nmfx_mesh_pad_lanes_total")
        return float(sum(rec["series"].values())) if rec else 0.0

    before = pads()
    ref = _run(two_group_data, "kl", mesh=None, restarts=5)
    got = _run(two_group_data, "kl", mesh=grid_mesh(4, 1, 1), restarts=5)
    assert pads() - before >= 3.0
    for field in _BIT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(ref, field)),
            np.asarray(getattr(got, field)),
            err_msg=f"padded restart mesh: {field} diverged")
