"""Multi-host path tests on the 8-device virtual CPU platform.

Single-process degenerate execution of the exact SPMD code multi-host runs
(SURVEY.md §4's multi-device test plan): the global mesh spans all 8 virtual
devices, restart sharding + replicated outputs compile and execute, and the
sharded result matches the unsharded one bit-for-bit (same keys, same math,
different device placement only).
"""

import jax
import numpy as np
import pytest

from nmfx import distributed as dist
from nmfx.config import SolverConfig
from nmfx.sweep import RESTART_AXIS, sweep_one_k


def test_global_mesh_covers_all_devices():
    mesh = dist.global_mesh()
    assert mesh.shape[RESTART_AXIS] == len(jax.devices()) == 8


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or try to reach a coordinator
    assert dist.is_coordinator()


def test_outputs_replicated_and_addressable(two_group_data):
    cfg = SolverConfig(algorithm="mu", max_iter=40)
    out = sweep_one_k(two_group_data, jax.random.key(0), k=2, restarts=16,
                      solver_cfg=cfg, mesh=dist.global_mesh())
    for name, x in zip(out._fields, out):
        if x is None:  # optional factor fields: absent without keep_factors
            assert name in ("all_w", "all_h")
            continue
        assert x.sharding.is_fully_replicated, name
        np.asarray(x)  # fully addressable on this (every) host


@pytest.mark.slow
def test_global_mesh_matches_single_device(two_group_data):
    cfg = SolverConfig(algorithm="mu", max_iter=40)
    plain = sweep_one_k(two_group_data, jax.random.key(3), k=3, restarts=16,
                        solver_cfg=cfg, mesh=None)
    meshed = sweep_one_k(two_group_data, jax.random.key(3), k=3, restarts=16,
                         solver_cfg=cfg, mesh=dist.global_mesh())
    np.testing.assert_allclose(np.asarray(plain.consensus),
                               np.asarray(meshed.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(plain.labels),
                                  np.asarray(meshed.labels))


def _assert_template_matches(real, tmpl):
    """Field-for-field structural equality between a real sweep output and
    the broadcast skeleton — including None-ness of the optional factor
    fields, or the broadcast pytrees disagree between hosts."""
    for name, r, t in zip(real._fields, real, tmpl):
        if r is None or t is None:
            assert r is None and t is None, name
            continue
        assert np.asarray(r).shape == t.shape, name
        assert np.asarray(r).dtype == t.dtype, name


def test_template_matches_real_output(two_group_data):
    """The broadcast skeleton must mirror sweep_one_k's structure exactly,
    or multi-host resume would die in broadcast_one_to_all."""
    from nmfx.sweep import _template

    cfg = SolverConfig(algorithm="mu", max_iter=20)
    real = sweep_one_k(two_group_data, jax.random.key(0), k=3, restarts=5,
                       solver_cfg=cfg)
    tmpl = _template(two_group_data, k=3, restarts=5, solver_cfg=cfg)
    _assert_template_matches(real, tmpl)


def test_template_matches_with_keep_factors(two_group_data):
    from nmfx.sweep import _template

    cfg = SolverConfig(algorithm="mu", max_iter=20)
    real = sweep_one_k(two_group_data, jax.random.key(0), k=3, restarts=5,
                       solver_cfg=cfg, keep_factors=True)
    tmpl = _template(two_group_data, k=3, restarts=5, solver_cfg=cfg,
                     keep_factors=True)
    assert real.all_w is not None and tmpl.all_w is not None
    _assert_template_matches(real, tmpl)


def test_distributed_consensus_end_to_end(two_group_data, tmp_path):
    res = dist.consensus(two_group_data, ks=(2, 3), restarts=8, max_iter=40,
                         seed=11)
    assert res.best_k == 2  # two planted groups
    assert set(res.per_k) == {2, 3}


def test_distributed_consensus_kl_on_grid_mesh(two_group_data):
    """kl over the distributed grid mesh (the solver the feature/sample
    axes exist for) end-to-end through dist.consensus."""
    res = dist.consensus(two_group_data, ks=(2,), restarts=4, max_iter=40,
                         seed=11, algorithm="kl",
                         feature_shards=2, sample_shards=2)
    assert res.best_k == 2
    assert res.per_k[2].consensus.shape == (
        two_group_data.shape[1], two_group_data.shape[1])


def test_global_mesh_grid_axes():
    from nmfx.sweep import FEATURE_AXIS, RESTART_AXIS, SAMPLE_AXIS

    mesh = dist.global_mesh(feature_shards=2, sample_shards=2)
    assert mesh.axis_names == (RESTART_AXIS, FEATURE_AXIS, SAMPLE_AXIS)
    assert mesh.shape[RESTART_AXIS] == 2  # 8 devices / (2*2)
    assert mesh.shape[FEATURE_AXIS] == 2
    assert mesh.shape[SAMPLE_AXIS] == 2
    with pytest.raises(ValueError, match="divide"):
        dist.global_mesh(feature_shards=3)  # 8 % 3 != 0


# ---------------------------------------------------------------------
# Elastic shard recovery (ISSUE 9): the durable-ledger counterpart of
# the fail-stop SPMD mesh — a shard (device) lost mid-sweep has its
# incomplete restart-chunks re-dispatched to the survivors (same key
# chains => same results), with zero stranded work.
# ---------------------------------------------------------------------

def _bit_identical(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            sv = np.ascontiguousarray(
                np.asarray(getattr(got.per_k[k], field)))
            qv = np.ascontiguousarray(
                np.asarray(getattr(ref.per_k[k], field)))
            assert sv.tobytes() == qv.tobytes(), f"{field} k={k}"
        assert got.per_k[k].rho == ref.per_k[k].rho


def test_elastic_shard_loss_recovers_exact(two_group_data, tmp_path):
    """Kill one of three shards mid-sweep (armed proc.preempt): the
    survivors re-dispatch its incomplete chunks and the result is
    bit-identical to the single-device checkpointed reference — zero
    stranded work, a complete ledger, and a dead heartbeat on record."""
    from nmfx import checkpoint as ckpt
    from nmfx import faults
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig

    scfg = SolverConfig(algorithm="mu", max_iter=40)
    kw = dict(ks=(2, 3), restarts=6, seed=5)
    ref = nmfconsensus(two_group_data, solver_cfg=scfg,
                       checkpoint=CheckpointConfig(
                           str(tmp_path / "ref"), every_n_restarts=2),
                       **kw)
    el_cfg = CheckpointConfig(str(tmp_path / "el"), every_n_restarts=2)
    faults.arm("proc.preempt", every=2, max_fires=1)
    try:
        res = dist.elastic_consensus(
            two_group_data, solver_cfg=scfg, checkpoint=el_cfg,
            devices=jax.devices()[:3], **kw)
    finally:
        faults.disarm("proc.preempt")
    _bit_identical(res, ref)
    # zero stranded work: every (k, chunk) unit committed a record
    import os

    assert len([n for n in os.listdir(tmp_path / "el")
                if n.startswith("k") and n.endswith(".npz")]) == 6
    # exactly one shard died (max_fires=1) and its heartbeat says so
    from nmfx.config import ConsensusConfig, InitConfig

    ck = ckpt.SweepCheckpoint.open(
        np.asarray(two_group_data),
        ConsensusConfig(ks=kw["ks"], restarts=kw["restarts"],
                        seed=kw["seed"]),
        scfg, InitConfig(), el_cfg)
    status = ck.shard_status()
    assert sum(1 for v in status.values() if not v["alive"]) == 1
    assert sum(1 for v in status.values() if v["alive"]) == 2


@pytest.mark.slow
def test_elastic_resumes_preempted_single_device_ledger(two_group_data,
                                                        tmp_path):
    """Cross-layer resume: a single-device checkpointed run killed
    mid-sweep leaves a partial ledger; the elastic runner opens the
    SAME ledger, dispatches only the missing units, and the final
    result is bit-identical to the uninterrupted reference."""
    from nmfx import checkpoint as ckpt
    from nmfx import faults
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig

    scfg = SolverConfig(algorithm="mu", max_iter=40)
    kw = dict(ks=(2, 3), restarts=6, seed=5)
    ref = nmfconsensus(two_group_data, solver_cfg=scfg,
                       checkpoint=CheckpointConfig(
                           str(tmp_path / "ref"), every_n_restarts=2),
                       **kw)
    cfg = CheckpointConfig(str(tmp_path / "c"), every_n_restarts=2)
    faults.arm("proc.preempt", every=3, max_fires=1)
    try:
        with pytest.raises(ckpt.Preempted):
            nmfconsensus(two_group_data, solver_cfg=scfg,
                         checkpoint=cfg, **kw)
    finally:
        faults.disarm("proc.preempt")
    before = ckpt.chunks_solved_count()
    res = dist.elastic_consensus(two_group_data, solver_cfg=scfg,
                                 checkpoint=cfg,
                                 devices=jax.devices()[:2], **kw)
    assert ckpt.chunks_solved_count() - before == 4  # 6 units - 2 kept
    _bit_identical(res, ref)


@pytest.mark.slow
def test_elastic_all_shards_dead_raises_then_resumes(two_group_data,
                                                     tmp_path):
    """Every shard dying leaves a typed error pointing at the ledger;
    a later (unarmed) run resumes it to the exact reference result —
    stranded work is a transient state, never a terminal one."""
    from nmfx import faults
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig

    scfg = SolverConfig(algorithm="mu", max_iter=40)
    kw = dict(ks=(2,), restarts=4, seed=5)
    cfg = CheckpointConfig(str(tmp_path / "c"), every_n_restarts=2)
    faults.arm("proc.preempt", every=1)  # every unit attempt preempts
    try:
        with pytest.raises(RuntimeError, match="re-run to resume"):
            dist.elastic_consensus(two_group_data, solver_cfg=scfg,
                                   checkpoint=cfg,
                                   devices=jax.devices()[:2], **kw)
    finally:
        faults.disarm("proc.preempt")
    ref = nmfconsensus(two_group_data, solver_cfg=scfg,
                       checkpoint=CheckpointConfig(
                           str(tmp_path / "ref"), every_n_restarts=2),
                       **kw)
    res = dist.elastic_consensus(two_group_data, solver_cfg=scfg,
                                 checkpoint=cfg,
                                 devices=jax.devices()[:2], **kw)
    _bit_identical(res, ref)


@pytest.mark.slow
def test_elastic_absorbed_crash_does_not_raise(two_group_data, tmp_path,
                                               monkeypatch):
    """A non-Preempted shard crash whose units the survivors absorbed
    is announced warn-once but NOT re-raised: the result is complete
    and exact (raising only when work strands is the elastic
    contract)."""
    from nmfx import checkpoint as ckpt
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig
    from nmfx.faults import _reset_warned

    _reset_warned()
    scfg = SolverConfig(algorithm="mu", max_iter=40)
    kw = dict(ks=(2,), restarts=4, seed=5)
    ref = nmfconsensus(two_group_data, solver_cfg=scfg,
                       checkpoint=CheckpointConfig(
                           str(tmp_path / "ref"), every_n_restarts=2),
                       **kw)
    real = ckpt.solve_chunk_host
    state = {"crashed": False}

    def crash_once(*args, **kwargs):
        if not state["crashed"]:
            state["crashed"] = True
            raise RuntimeError("transient device error")
        return real(*args, **kwargs)

    monkeypatch.setattr(ckpt, "solve_chunk_host", crash_once)
    with pytest.warns(RuntimeWarning, match="crashed"):
        res = dist.elastic_consensus(
            two_group_data, solver_cfg=scfg,
            checkpoint=CheckpointConfig(str(tmp_path / "el"),
                                        every_n_restarts=2),
            devices=jax.devices()[:2], **kw)
    _bit_identical(res, ref)


# ---------------------------------------------------------------------
# replica mesh specs (ISSUE 19): grammar, device fit, meshed shards
# ---------------------------------------------------------------------

def test_parse_mesh_spec_grammar():
    assert dist.parse_mesh_spec("4") == (4, 1, 1)
    assert dist.parse_mesh_spec("2x2") == (2, 2, 1)
    assert dist.parse_mesh_spec("2x2x2") == (2, 2, 2)
    assert dist.parse_mesh_spec("1") == (1, 1, 1)
    for bad in ("", "ax2", "2x", "0", "2x0", "-1", "2x2x2x2"):
        with pytest.raises(dist.MeshSpecError):
            dist.parse_mesh_spec(bad)
    # the typed error is still a ValueError for legacy handlers
    assert issubclass(dist.MeshSpecError, ValueError)


def test_build_replica_mesh_default_devices_prefix():
    from nmfx.sweep import RESTART_AXIS

    mesh = dist.build_replica_mesh("4")
    assert mesh.shape[RESTART_AXIS] == 4
    assert list(mesh.devices.flat) == jax.devices()[:4]
    with pytest.raises(dist.MeshSpecError, match="needs 16 device"):
        dist.build_replica_mesh("16")  # this process has only 8


def test_build_replica_mesh_explicit_devices_exact_count():
    """A pool-carved device block must be consumed EXACTLY: a replica
    owning more chips than its mesh uses would idle capacity the
    router still prices — typed error, not truncation."""
    devs = jax.devices()
    mesh = dist.build_replica_mesh("2", devices=devs[:2])
    assert list(mesh.devices.flat) == devs[:2]
    with pytest.raises(dist.MeshSpecError, match="exactly 2"):
        dist.build_replica_mesh("2", devices=devs[:4])
    with pytest.raises(dist.MeshSpecError, match="exactly 4"):
        dist.build_replica_mesh("2x2", devices=devs[:2])


def test_build_replica_mesh_grid_axes():
    from nmfx.sweep import FEATURE_AXIS, RESTART_AXIS, SAMPLE_AXIS

    mesh = dist.build_replica_mesh("2x2x2")
    assert mesh.shape[RESTART_AXIS] == 2
    assert mesh.shape[FEATURE_AXIS] == 2
    assert mesh.shape[SAMPLE_AXIS] == 2


def test_elastic_shard_devices_uneven_counts_typed(two_group_data,
                                                   tmp_path):
    """Meshed elastic mode rejects device counts that don't tile: a
    ragged remainder would idle devices silently."""
    from nmfx import checkpoint as ckpt
    from nmfx.config import (CheckpointConfig, ConsensusConfig,
                             InitConfig, SolverConfig)

    ccfg = ConsensusConfig(ks=(2,), restarts=4, seed=5)
    scfg, icfg = SolverConfig(algorithm="mu", max_iter=10), InitConfig()
    ck = ckpt.SweepCheckpoint.open(
        np.asarray(two_group_data), ccfg, scfg, icfg,
        CheckpointConfig(str(tmp_path / "ck"), every_n_restarts=2))
    mk = lambda **kw: dist.ElasticShardRunner(
        ck, ccfg, scfg, icfg, np.asarray(two_group_data), **kw)
    with pytest.raises(dist.MeshSpecError, match=">= 1"):
        mk(shard_devices=0)
    with pytest.raises(dist.MeshSpecError, match="exceeds"):
        mk(devices=jax.devices()[:2], shard_devices=4)
    with pytest.raises(dist.MeshSpecError, match="divide"):
        mk(devices=jax.devices()[:6], shard_devices=4)
    # an even tiling builds sub-mesh groups, one worker per group
    r = mk(devices=jax.devices()[:6], shard_devices=2)
    assert [len(g) for g in r._groups] == [2, 2, 2]


@pytest.mark.slow
def test_elastic_meshed_shards_bit_identical(two_group_data, tmp_path):
    """shard_devices=2 over 4 devices (2 meshed shards) must match the
    single-device checkpointed run bit-for-bit — the meshed executor
    draws the same canonical keys and commits the same records. kl
    rides the vmapped generic driver, the only family the meshed chunk
    executor accepts (packed-mu pool geometry is composition-dependent,
    so it is typed-rejected rather than silently divergent)."""
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig

    scfg = SolverConfig(algorithm="kl", max_iter=40)
    kw = dict(ks=(2, 3), restarts=6, seed=5)
    ref = nmfconsensus(two_group_data, solver_cfg=scfg,
                       checkpoint=CheckpointConfig(
                           str(tmp_path / "ref"), every_n_restarts=2),
                       **kw)
    res = dist.elastic_consensus(
        two_group_data, solver_cfg=scfg,
        checkpoint=CheckpointConfig(str(tmp_path / "mesh"),
                                    every_n_restarts=2),
        devices=jax.devices()[:4], shard_devices=2, **kw)
    _bit_identical(res, ref)
