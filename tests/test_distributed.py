"""Multi-host path tests on the 8-device virtual CPU platform.

Single-process degenerate execution of the exact SPMD code multi-host runs
(SURVEY.md §4's multi-device test plan): the global mesh spans all 8 virtual
devices, restart sharding + replicated outputs compile and execute, and the
sharded result matches the unsharded one bit-for-bit (same keys, same math,
different device placement only).
"""

import jax
import numpy as np
import pytest

from nmfx import distributed as dist
from nmfx.config import SolverConfig
from nmfx.sweep import RESTART_AXIS, sweep_one_k


def test_global_mesh_covers_all_devices():
    mesh = dist.global_mesh()
    assert mesh.shape[RESTART_AXIS] == len(jax.devices()) == 8


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or try to reach a coordinator
    assert dist.is_coordinator()


def test_outputs_replicated_and_addressable(two_group_data):
    cfg = SolverConfig(algorithm="mu", max_iter=40)
    out = sweep_one_k(two_group_data, jax.random.key(0), k=2, restarts=16,
                      solver_cfg=cfg, mesh=dist.global_mesh())
    for name, x in zip(out._fields, out):
        if x is None:  # optional factor fields: absent without keep_factors
            assert name in ("all_w", "all_h")
            continue
        assert x.sharding.is_fully_replicated, name
        np.asarray(x)  # fully addressable on this (every) host


@pytest.mark.slow
def test_global_mesh_matches_single_device(two_group_data):
    cfg = SolverConfig(algorithm="mu", max_iter=40)
    plain = sweep_one_k(two_group_data, jax.random.key(3), k=3, restarts=16,
                        solver_cfg=cfg, mesh=None)
    meshed = sweep_one_k(two_group_data, jax.random.key(3), k=3, restarts=16,
                         solver_cfg=cfg, mesh=dist.global_mesh())
    np.testing.assert_allclose(np.asarray(plain.consensus),
                               np.asarray(meshed.consensus), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(plain.labels),
                                  np.asarray(meshed.labels))


def _assert_template_matches(real, tmpl):
    """Field-for-field structural equality between a real sweep output and
    the broadcast skeleton — including None-ness of the optional factor
    fields, or the broadcast pytrees disagree between hosts."""
    for name, r, t in zip(real._fields, real, tmpl):
        if r is None or t is None:
            assert r is None and t is None, name
            continue
        assert np.asarray(r).shape == t.shape, name
        assert np.asarray(r).dtype == t.dtype, name


def test_template_matches_real_output(two_group_data):
    """The broadcast skeleton must mirror sweep_one_k's structure exactly,
    or multi-host resume would die in broadcast_one_to_all."""
    from nmfx.sweep import _template

    cfg = SolverConfig(algorithm="mu", max_iter=20)
    real = sweep_one_k(two_group_data, jax.random.key(0), k=3, restarts=5,
                       solver_cfg=cfg)
    tmpl = _template(two_group_data, k=3, restarts=5, solver_cfg=cfg)
    _assert_template_matches(real, tmpl)


def test_template_matches_with_keep_factors(two_group_data):
    from nmfx.sweep import _template

    cfg = SolverConfig(algorithm="mu", max_iter=20)
    real = sweep_one_k(two_group_data, jax.random.key(0), k=3, restarts=5,
                       solver_cfg=cfg, keep_factors=True)
    tmpl = _template(two_group_data, k=3, restarts=5, solver_cfg=cfg,
                     keep_factors=True)
    assert real.all_w is not None and tmpl.all_w is not None
    _assert_template_matches(real, tmpl)


def test_distributed_consensus_end_to_end(two_group_data, tmp_path):
    res = dist.consensus(two_group_data, ks=(2, 3), restarts=8, max_iter=40,
                         seed=11)
    assert res.best_k == 2  # two planted groups
    assert set(res.per_k) == {2, 3}


def test_distributed_consensus_kl_on_grid_mesh(two_group_data):
    """kl over the distributed grid mesh (the solver the feature/sample
    axes exist for) end-to-end through dist.consensus."""
    res = dist.consensus(two_group_data, ks=(2,), restarts=4, max_iter=40,
                         seed=11, algorithm="kl",
                         feature_shards=2, sample_shards=2)
    assert res.best_k == 2
    assert res.per_k[2].consensus.shape == (
        two_group_data.shape[1], two_group_data.shape[1])


def test_global_mesh_grid_axes():
    from nmfx.sweep import FEATURE_AXIS, RESTART_AXIS, SAMPLE_AXIS

    mesh = dist.global_mesh(feature_shards=2, sample_shards=2)
    assert mesh.axis_names == (RESTART_AXIS, FEATURE_AXIS, SAMPLE_AXIS)
    assert mesh.shape[RESTART_AXIS] == 2  # 8 devices / (2*2)
    assert mesh.shape[FEATURE_AXIS] == 2
    assert mesh.shape[SAMPLE_AXIS] == 2
    with pytest.raises(ValueError, match="divide"):
        dist.global_mesh(feature_shards=3)  # 8 % 3 != 0
