"""Request coalescing (ISSUE 16): concurrent identical submissions
attach to ONE in-flight solve — at the server, at the router, and
across replica failover (the satellite acceptance shape: the leader's
replica SIGKILLed mid-solve with followers attached — zero lost
futures, exactly one re-dispatch, bit-identical results).

Server/router mechanics run against the scriptable
:class:`test_serve.FakeEngine` (milliseconds, no device dispatch); the
cross-process failover half uses two subprocess replicas like
tests/test_router.py's SIGKILL recovery test."""

import concurrent.futures

import numpy as np
import pytest

from test_router import _assert_bit_equal, _fast_cfg, _pool, _sticky_id, \
    _worker_env
from test_serve import FakeEngine, _mat

from nmfx.replica import ReplicaPool
from nmfx.router import NMFXRouter, RouterConfig
from nmfx.serve import NMFXServer, ServeConfig

KW = dict(ks=(2,), restarts=2, seed=7)


# ---------------------------------------------------------------------
# server-level coalescing
# ---------------------------------------------------------------------

def test_server_coalesce_is_opt_in():
    assert ServeConfig().coalesce_requests is False


def test_identical_submissions_share_one_dispatch():
    eng = FakeEngine(compat=None)
    a = _mat()
    with NMFXServer(ServeConfig(coalesce_requests=True), engine=eng,
                    start=False) as srv:
        leader = srv.submit(a, **KW)
        f2 = srv.submit(a, **KW)
        f3 = srv.submit(a, **KW)
        assert srv.stats()["coalesced"] == 2
        srv.resume()
        r1 = leader.result(timeout=60)
        # followers share the leader's outcome — the SAME object
        assert f2.result(timeout=60) is r1
        assert f3.result(timeout=60) is r1
    assert len(eng.solo) == 1          # exactly one dispatch
    st = srv.stats()
    assert st["submitted"] == 3 and st["completed"] == 3
    assert f2.stats.latency_s is not None


def test_different_config_never_coalesces():
    eng = FakeEngine(compat=None)
    a = _mat()
    with NMFXServer(ServeConfig(coalesce_requests=True), engine=eng,
                    start=False) as srv:
        f1 = srv.submit(a, **KW)
        f2 = srv.submit(a, **dict(KW, seed=8))   # different key
        srv.resume()
        assert f1.result(timeout=60) is not f2.result(timeout=60)
    assert srv.stats()["coalesced"] == 0
    assert len(eng.solo) == 2


def test_deadline_requests_never_coalesce():
    """A deadline'd submission bypasses coalescing entirely — a shared
    outcome cannot honor a latency contract it never saw."""
    eng = FakeEngine(compat=None)
    a = _mat()
    with NMFXServer(ServeConfig(coalesce_requests=True), engine=eng,
                    start=False) as srv:
        f1 = srv.submit(a, **KW)
        f2 = srv.submit(a, timeout=120.0, **KW)
        srv.resume()
        f1.result(timeout=60), f2.result(timeout=60)
    assert srv.stats()["coalesced"] == 0
    assert len(eng.solo) == 2


def test_coalesced_error_fans_out_typed():
    class FailingEngine(FakeEngine):
        def dispatch_solo(self, req, placed, scfg):
            raise RuntimeError("engine exploded")

    eng = FailingEngine(compat=None)
    a = _mat()
    with NMFXServer(ServeConfig(coalesce_requests=True,
                                dispatch_retries=0),
                    engine=eng, start=False) as srv:
        f1 = srv.submit(a, **KW)
        f2 = srv.submit(a, **KW)
        srv.resume()
        with pytest.raises(Exception):
            f1.result(timeout=60)
        with pytest.raises(Exception):
            f2.result(timeout=60)     # follower resolves too: no hang
    st = srv.stats()
    assert st["coalesced"] == 1 and st["failed"] == 2


def test_cancelled_leader_promotes_follower():
    """Cancelling the leader pre-dispatch must not cancel its
    followers: the first live follower is promoted into the queue and
    the rest re-attach to it."""
    eng = FakeEngine(compat=None)
    a = _mat()
    with NMFXServer(ServeConfig(coalesce_requests=True), engine=eng,
                    start=False) as srv:
        leader = srv.submit(a, **KW)
        f2 = srv.submit(a, **KW)
        f3 = srv.submit(a, **KW)
        assert leader.cancel()
        srv.resume()
        r2 = f2.result(timeout=60)
        assert f3.result(timeout=60) is r2
        with pytest.raises(concurrent.futures.CancelledError):
            leader.result(timeout=60)
    assert len(eng.solo) == 1          # the promoted follower's solve


def test_coalesce_composes_with_result_cache(tmp_path):
    """Mixed economics in one server: first wave coalesces onto one
    solve, whose finished result then serves a later identical
    submission from the cache with no dispatch at all."""
    eng = FakeEngine(compat=None)
    a = _mat()
    cfg = ServeConfig(coalesce_requests=True,
                      result_cache_dir=str(tmp_path))
    with NMFXServer(cfg, engine=eng, start=False) as srv:
        f1 = srv.submit(a, **KW)
        f2 = srv.submit(a, **KW)
        srv.resume()
        r1 = f1.result(timeout=60)
        assert f2.result(timeout=60) is r1
        f4 = srv.submit(a, **KW)
        assert f4.result(timeout=60) is not None
        st = srv.stats()
    assert len(eng.solo) == 1
    assert st["coalesced"] == 1 and st["result_cache_hits"] == 1
    assert st["submitted"] == 3 and st["completed"] == 3


# ---------------------------------------------------------------------
# router-level coalescing (thread replicas)
# ---------------------------------------------------------------------

def test_router_coalesce_single_forward(tmp_path):
    a = _mat()
    pool = _pool(tmp_path, n=2,
                 engine_factory=lambda: FakeEngine(compat=None,
                                                   delay=0.4))
    with NMFXRouter(pool, _fast_cfg(coalesce_requests=True)) as router:
        leader = router.submit(a, **KW)
        f2 = router.submit(a, **KW)
        f3 = router.submit(a, **KW)
        s_mid = router.stats()
        r1 = leader.result(timeout=60)
        assert f2.result(timeout=60) is r1
        assert f3.result(timeout=60) is r1
        s = router.stats()
    assert s_mid["coalesced"] == 2
    assert s["completed"] == 3 and s["failed"] == 0
    # followers were never forwarded — no replica ever saw them
    assert leader.stats.replica is not None
    assert f2.stats.replica is None and f3.stats.replica is None


def test_router_coalesce_is_opt_in(tmp_path):
    assert RouterConfig().coalesce_requests is False
    a = _mat()
    pool = _pool(tmp_path, n=1,
                 engine_factory=lambda: FakeEngine(compat=None,
                                                   delay=0.2))
    with NMFXRouter(pool, _fast_cfg()) as router:
        f1 = router.submit(a, **KW)
        f2 = router.submit(a, **KW)
        f1.result(timeout=60), f2.result(timeout=60)
    assert router.stats()["coalesced"] == 0


# ---------------------------------------------------------------------
# the satellite acceptance shape: coalescing × replica failover
# ---------------------------------------------------------------------

def test_coalesced_followers_survive_leader_replica_sigkill(tmp_path):
    """The leader's subprocess replica is SIGKILLed mid-solve with two
    followers coalesced onto it. The router reclaims the leader's
    write-ahead record and re-dispatches it on the survivor — EXACTLY
    once (followers were never forwarded, so there is nothing else to
    readmit) — and the whole cohort resolves bit-identically to a solo
    run. Zero lost futures."""
    from nmfx.api import nmfconsensus
    from nmfx.config import SolverConfig
    from nmfx.datasets import two_group_matrix
    from nmfx.exec_cache import ExecCache

    a = two_group_matrix(n_genes=60, n_per_group=10, seed=3)
    scfg = SolverConfig(max_iter=30)
    pool = ReplicaPool(2, root=str(tmp_path / "pool"), mode="process",
                       env=_worker_env())
    with NMFXRouter(pool, _fast_cfg(stickiness_slack=8,
                                    coalesce_requests=True)) as router:
        victim_id = _sticky_id(router, a)
        victim = pool.get(victim_id)
        leader = router.submit(a, solver_cfg=scfg, **KW)
        assert leader.stats.replica == victim_id
        followers = [router.submit(a, solver_cfg=scfg, **KW)
                     for _ in range(2)]
        assert router.stats()["coalesced"] == 2
        victim.kill()
        results = [f.result(timeout=180)
                   for f in [leader] + followers]      # zero lost futures
    ref = nmfconsensus(a, solver_cfg=scfg, use_mesh=False,
                       exec_cache=ExecCache(), **KW)
    for res in results:
        _assert_bit_equal(res, ref)
    s = router.stats()
    assert s["recovered"] == 1
    assert s["readmitted"] == 1        # exactly one re-dispatch
    assert s["completed"] == 3 and s["failed"] == 0
