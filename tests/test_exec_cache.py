"""Executable-reuse serving layer (nmfx/exec_cache.py): bucket policy,
hit/miss keying, LRU eviction, disk persistence (fresh-process
zero-compile cold start, corruption/mismatch fallback, byte-capped
mtime-LRU), the pipelined parallel-compile paths, and — the load-bearing
property — exact numerical equivalence of padded-bucket sweeps to
exact-shape sweeps."""

import dataclasses
import json
import os
import pickle
import subprocess
import sys
import textwrap
import warnings

import jax
import numpy as np
import pytest

from nmfx.config import ConsensusConfig, ExecCacheConfig, InitConfig, \
    SolverConfig
from nmfx.exec_cache import (ExecCache, bucket_dim, persist_key_fields,
                             start_host_fetch)
from nmfx.sweep import sweep

CCFG = ConsensusConfig(ks=(2, 3), restarts=6, seed=3, grid_exec="grid",
                       grid_slots=4)
SCFG = SolverConfig(max_iter=200)


@pytest.fixture(scope="module")
def serve_data():
    from nmfx.datasets import two_group_matrix

    # two different true shapes that share a bucket under the default
    # lattice (both round up to (256, 64))
    return (two_group_matrix(n_genes=120, n_per_group=12, seed=7),
            two_group_matrix(n_genes=100, n_per_group=10, seed=9))


# --- bucket policy --------------------------------------------------------

def test_bucket_dim_properties():
    for q in (64, 256):
        prev = 0
        for x in (1, q - 1, q, q + 1, 7 * q, 8 * q + 1, 1000, 5000, 99999):
            b = bucket_dim(x, q)
            assert b >= x
            assert b % q == 0
            assert b >= prev or x < prev  # monotonic in x
            # bounded relative padding: the step stops doubling once
            # step·growth_steps >= x, so step <= x/(growth_steps/2)
            assert b <= x * (1 + 2 / 8) + q
            prev = b


def test_bucket_north_star_lands_on_probed_boundary_shape():
    cache = ExecCache()
    # the hardware-probed VMEM boundary shape (bench.py --verify stage 3)
    assert cache.bucket_shape(5000, 500) == (5120, 512)
    assert cache.bucket_shape(4832, 488) == (5120, 512)  # same bucket


def test_bucket_dim_rejects_nonpositive():
    with pytest.raises(ValueError):
        bucket_dim(0, 64)


# --- keying / LRU ---------------------------------------------------------

#: keying/LRU tests compile real executables — keep them tiny (one rank,
#: two restarts) so the suite's compile budget goes to the equivalence
#: tests instead
_CCFG_TINY = ConsensusConfig(ks=(2,), restarts=2, seed=3,
                             grid_exec="grid", grid_slots=2)
_SCFG_TINY = SolverConfig(max_iter=20)


def test_same_bucket_hits_different_config_misses(serve_data):
    a1, a2 = serve_data
    cache = ExecCache()
    cache.executable(a1.shape, _CCFG_TINY, _SCFG_TINY)
    assert cache.stats["misses"] == 1
    _, hit = cache.executable(a2.shape, _CCFG_TINY, _SCFG_TINY)  # same bucket
    assert hit and cache.stats["hits"] == 1
    # any solver-config change re-keys (the config fingerprint)
    _, hit = cache.executable(
        a1.shape, _CCFG_TINY, dataclasses.replace(_SCFG_TINY, max_iter=30))
    assert not hit
    # so does the rank set / restart count / label rule
    _, hit = cache.executable(
        a1.shape, dataclasses.replace(_CCFG_TINY, restarts=3), _SCFG_TINY)
    assert not hit
    assert cache.stats["misses"] == 3


def test_lru_eviction_order():
    cache = ExecCache(ExecCacheConfig(max_entries=2))
    cfgs = [dataclasses.replace(_SCFG_TINY, max_iter=20 + 2 * i)
            for i in range(3)]
    for c in cfgs:
        cache.executable((60, 20), _CCFG_TINY, c)
    assert cache.stats["entries"] == 2
    assert cache.stats["evictions"] == 1
    # evicted: recompile
    _, hit = cache.executable((60, 20), _CCFG_TINY, cfgs[0])
    assert not hit
    _, hit = cache.executable((60, 20), _CCFG_TINY, cfgs[2])  # resident
    assert hit


def test_cacheable_gating():
    cache = ExecCache()
    assert cache.cacheable(CCFG, SCFG, None)
    # pg has no dense-batched block — the scheduler can't run it
    assert not cache.cacheable(CCFG, SolverConfig(algorithm="pg"), None)
    assert not cache.cacheable(
        dataclasses.replace(CCFG, grid_exec="per_k"), SCFG, None)
    with pytest.raises(ValueError):
        cache.run_sweep(np.ones((8, 4)),
                        dataclasses.replace(CCFG, grid_exec="per_k"), SCFG)


# --- padded-bucket numerical equivalence ----------------------------------

@pytest.mark.parametrize("mesh_on", [
    False, pytest.param(True, marks=pytest.mark.slow)])
def test_padded_equivalence_to_exact_sweep(serve_data, mesh_on):
    """The acceptance property: a bucketed sweep (padded A, masked
    consensus, rescaled dnorms, threaded flip budget) must reproduce the
    exact-shape sweep — consensus allclose and identical labels — for
    BOTH true shapes sharing the bucket."""
    from nmfx.sweep import default_mesh

    mesh = default_mesh() if mesh_on else None
    cache = ExecCache()
    icfg = InitConfig()
    for a in serve_data:
        ref = sweep(a, CCFG, SCFG, icfg, mesh)
        got = cache.run_sweep(a, CCFG, SCFG, icfg, mesh)
        for k in CCFG.ks:
            np.testing.assert_array_equal(np.asarray(got[k].labels),
                                          np.asarray(ref[k].labels))
            np.testing.assert_allclose(np.asarray(got[k].consensus),
                                       np.asarray(ref[k].consensus),
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(got[k].dnorms),
                                       np.asarray(ref[k].dnorms),
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(got[k].iterations),
                                          np.asarray(ref[k].iterations))
            assert got[k].consensus.shape == (a.shape[1], a.shape[1])
            assert got[k].best_w.shape == (a.shape[0], k)
            assert got[k].best_h.shape == (k, a.shape[1])
    # both shapes served from one executable
    assert cache.stats == {**cache.stats, "entries": 1, "misses": 1,
                           "hits": 1}


def test_keep_factors_unpadded(serve_data):
    a, _ = serve_data
    cache = ExecCache()
    ccfg = dataclasses.replace(CCFG, keep_factors=True)
    out = cache.run_sweep(a, ccfg, SCFG, InitConfig())
    m, n = a.shape
    for k in ccfg.ks:
        assert out[k].all_w.shape == (ccfg.restarts, m, k)
        assert out[k].all_h.shape == (ccfg.restarts, k, n)


def test_prefetch_handle_round_trip(serve_data):
    a, _ = serve_data
    cache = ExecCache()
    placed = cache.prefetch(a, SCFG)
    assert placed.true_shape == a.shape
    assert placed.a_pad.shape == placed.bucket
    out = cache.run_sweep(placed, CCFG, SCFG, InitConfig())
    ref = cache.run_sweep(a, CCFG, SCFG, InitConfig())
    for k in CCFG.ks:
        np.testing.assert_array_equal(np.asarray(out[k].labels),
                                      np.asarray(ref[k].labels))


def test_start_host_fetch_is_safe_everywhere():
    # arrays, Nones, nested pytrees — never raises, never blocks
    import jax.numpy as jnp

    start_host_fetch({"x": jnp.ones((3,)), "y": None,
                      "z": [np.ones(2), jnp.zeros(())]})


def test_threefry_flat_index_properties():
    """The two partitionable-threefry properties the inside-executable
    init (sweep._dyn_lane_init) rests on: draws are counter-based per
    FLAT element index, so (a) same-column-count draws are
    row-prefix-stable and (b) a 1-D draw gathered at i·n_true + j equals
    the true 2-D draw. If a jax upgrade ever breaks these, the bucketed
    executables would silently produce different (still valid, but not
    exact-sweep-equal) restarts — fail here instead."""
    import jax.numpy as jnp

    key = jax.random.key(42)
    wp = jax.random.uniform(key, (1024, 3), jnp.float32, 0.2, 0.9)
    wt = jax.random.uniform(key, (970, 3), jnp.float32, 0.2, 0.9)
    np.testing.assert_array_equal(np.asarray(wp[:970]), np.asarray(wt))
    hu = jax.random.uniform(key, (3 * 256,), jnp.float32, 0.2, 0.9)
    ht = jax.random.uniform(key, (3, 197), jnp.float32, 0.2, 0.9)
    i = jnp.arange(3)[:, None]
    j = jnp.arange(197)[None, :]
    np.testing.assert_array_equal(np.asarray(hu[i * 197 + j]),
                                  np.asarray(ht))


# --- disk persistence -----------------------------------------------------
# Tier-1 budget note: every persistence test compiles (at most) the
# smallest viable executables — one rank, two restarts, max_iter<=30 on a
# 60x20 matrix — so the whole section stays within seconds per compile on
# the CPU-only container.

_A_SMALL = np.random.default_rng(0).uniform(0.1, 1.0, (60, 20))


def _disk_cache(tmp_path, **kw):
    return ExecCache(ExecCacheConfig(cache_dir=str(tmp_path / "exec"),
                                     **kw))


def _entry_files(tmp_path):
    d = tmp_path / "exec"
    return sorted(p for p in os.listdir(d) if p.endswith(".nmfxexec"))


def test_persist_fresh_instance_serves_from_disk(tmp_path):
    """A second cache instance (standing in for a fresh process — the
    real cross-process contract is pinned by the subprocess test below)
    deserializes the persisted executable instead of recompiling, and
    the served results are identical."""
    c1 = _disk_cache(tmp_path)
    o1 = c1.run_sweep(_A_SMALL, _CCFG_TINY, _SCFG_TINY, InitConfig())
    assert c1.stats["persist_misses"] == 1 and c1.misses == 1
    assert len(_entry_files(tmp_path)) == 1
    c2 = _disk_cache(tmp_path)
    o2 = c2.run_sweep(_A_SMALL, _CCFG_TINY, _SCFG_TINY, InitConfig())
    assert c2.stats["persist_hits"] == 1
    assert c2.misses == 0  # deserialize-and-dispatch, no compile
    np.testing.assert_array_equal(np.asarray(o1[2].labels),
                                  np.asarray(o2[2].labels))
    np.testing.assert_array_equal(np.asarray(o1[2].dnorms),
                                  np.asarray(o2[2].dnorms))


def test_memory_eviction_keeps_disk_entry_readmission_is_hit(tmp_path):
    """The two LRUs are independent: evicting an executable from the
    in-memory LRU must NOT delete its disk entry, and re-admitting it
    from disk is a (persist) hit, not a recompile."""
    cache = _disk_cache(tmp_path, max_entries=1)
    cfg_a = _SCFG_TINY
    cfg_b = dataclasses.replace(_SCFG_TINY, max_iter=22)
    cache.executable((60, 20), _CCFG_TINY, cfg_a)
    cache.executable((60, 20), _CCFG_TINY, cfg_b)  # evicts A from memory
    assert cache.stats["evictions"] == 1 and cache.misses == 2
    assert len(_entry_files(tmp_path)) == 2  # both disk entries survive
    _, hit = cache.executable((60, 20), _CCFG_TINY, cfg_a)
    assert hit  # re-admission from disk IS a hit
    assert cache.stats["persist_hits"] == 1
    assert cache.misses == 2  # no recompile happened
    assert cache.stats["disk_evictions"] == 0


def test_corrupt_entry_falls_back_with_one_warning(tmp_path):
    """A truncated/corrupt cache file must degrade to a clean recompile
    — one warning per instance, never a crash — and the recompile
    re-publishes a valid entry."""
    c1 = _disk_cache(tmp_path)
    cfg_b = dataclasses.replace(_SCFG_TINY, max_iter=24)
    c1.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    c1.executable((60, 20), _CCFG_TINY, cfg_b)
    for name in _entry_files(tmp_path):
        path = tmp_path / "exec" / name
        path.write_bytes(path.read_bytes()[:10])  # truncate both
    c2 = _disk_cache(tmp_path)
    with pytest.warns(RuntimeWarning, match="recompiling"):
        _, hit = c2.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    assert not hit and c2.misses == 1
    # second corrupt entry in the SAME instance: silent fallback (the
    # warning fired once), still a clean recompile
    import warnings as _warnings

    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        _, hit = c2.executable((60, 20), _CCFG_TINY, cfg_b)
    assert not hit and c2.misses == 2
    assert not [w for w in caught
                if issubclass(w.category, RuntimeWarning)]
    # the fallback republished a valid entry: a third instance hits disk
    c3 = _disk_cache(tmp_path)
    _, hit = c3.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    assert hit and c3.stats["persist_hits"] == 1


def test_env_mismatched_entry_falls_back_with_warning(tmp_path):
    """An entry whose stored key disagrees with this process's (a stale
    jax/jaxlib/device environment — simulated by editing the stored key,
    since the live environment can't be swapped mid-test) recompiles
    with one warning instead of deserializing the wrong executable."""
    c1 = _disk_cache(tmp_path)
    c1.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    (name,) = _entry_files(tmp_path)
    path = tmp_path / "exec" / name
    rec = pickle.loads(path.read_bytes())
    rec["key"] += "-written-under-different-jax"
    path.write_bytes(pickle.dumps(rec))
    c2 = _disk_cache(tmp_path)
    with pytest.warns(RuntimeWarning, match="recompiling"):
        _, hit = c2.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    assert not hit and c2.misses == 1
    # the mismatched entry was replaced by a valid one
    c3 = _disk_cache(tmp_path)
    _, hit = c3.executable((60, 20), _CCFG_TINY, _SCFG_TINY)
    assert hit


def test_disk_byte_cap_evicts_mtime_lru(tmp_path):
    """Byte-capped disk eviction drops oldest-mtime entries first and
    never the just-written one — exercised directly on crafted files so
    the test pays zero compiles."""
    cache = _disk_cache(tmp_path, max_disk_bytes=3000)
    d = tmp_path / "exec"
    d.mkdir()
    paths = []
    for i, name in enumerate(("old", "mid", "new")):
        p = d / f"{name}.nmfxexec"
        p.write_bytes(b"x" * 1500)
        os.utime(p, (1_000_000 + i, 1_000_000 + i))
        paths.append(p)
    cache._evict_disk(keep=str(paths[2]))
    assert not paths[0].exists()  # oldest evicted
    assert paths[1].exists() and paths[2].exists()
    assert cache.stats["disk_evictions"] == 1
    # the protected entry survives even when it alone exceeds the cap
    tight = _disk_cache(tmp_path, max_disk_bytes=100)
    tight._evict_disk(keep=str(paths[2]))
    assert paths[2].exists()
    assert not paths[1].exists()


def test_persist_key_fields_cover_all_solver_fields():
    """The NMFX001 persistent-key hook: today every SolverConfig field
    renders into the disk key's repr. A field added with repr=False
    shrinks this set and fails lint (tests/test_lint_rules.py)."""
    assert persist_key_fields() == frozenset(
        f.name for f in dataclasses.fields(SolverConfig))


# --- pipelined / background compilation -----------------------------------

def test_background_warm_dedupes_with_foreground_request():
    """A request arriving while a background warm is compiling the same
    executable WAITS on the in-flight compile instead of duplicating it:
    exactly one compile total."""
    cache = ExecCache()
    task = cache.warm([_A_SMALL.shape], _CCFG_TINY, _SCFG_TINY,
                      background=True)
    out = cache.run_sweep(_A_SMALL, _CCFG_TINY, _SCFG_TINY, InitConfig())
    report = task.result()
    assert len(report) == 1
    assert cache.misses == 1  # one compile despite the concurrency
    assert cache.stats["entries"] == 1
    assert out[2].labels.shape == (_CCFG_TINY.restarts, _A_SMALL.shape[1])


def test_warm_parallel_compiles_multiple_buckets():
    """warm() builds multiple pending buckets concurrently in the thread
    pool — both land, each reported once."""
    cache = ExecCache(ExecCacheConfig(compile_workers=2))
    report = cache.warm([(60, 20), (40, 100)], _CCFG_TINY, _SCFG_TINY)
    assert len(report) == 2
    assert {tuple(r["bucket"]) for r in report} == {(256, 64), (256, 128)}
    assert all(not r["cache_hit"] and r["source"] == "compile"
               for r in report)
    assert cache.misses == 2 and cache.stats["entries"] == 2


def test_pipeline_ranks_matches_single_rank_grid_sweeps():
    """ExecCacheConfig.pipeline_ranks: each rank is served by its own
    concurrently-compiled bucketed executable, and each rank's results
    are EXACTLY a single-rank grid sweep's (the mode's documented
    contract; it matches the whole-grid default only to float
    tolerance, which is why it is opt-in)."""
    ccfg = ConsensusConfig(ks=(2, 3), restarts=2, seed=3,
                           grid_exec="grid", grid_slots=2)
    scfg = SolverConfig(max_iter=30)
    cache = ExecCache(ExecCacheConfig(pipeline_ranks=True,
                                      compile_workers=2))
    out = cache.run_sweep(_A_SMALL, ccfg, scfg, InitConfig())
    assert cache.misses == 2  # one executable per rank
    for k in ccfg.ks:
        ref = sweep(_A_SMALL,
                    dataclasses.replace(ccfg, ks=(k,)), scfg,
                    InitConfig(), None)
        np.testing.assert_array_equal(np.asarray(out[k].labels),
                                      np.asarray(ref[k].labels))
        np.testing.assert_array_equal(np.asarray(out[k].iterations),
                                      np.asarray(ref[k].iterations))
        np.testing.assert_allclose(np.asarray(out[k].consensus),
                                   np.asarray(ref[k].consensus),
                                   atol=1e-6)
        assert out[k].consensus.shape == (20, 20)
    # a repeat request is fully compile-free through the per-rank entries
    cache.run_sweep(_A_SMALL, ccfg, scfg, InitConfig())
    assert cache.misses == 2 and cache.hits == 2


def test_pipeline_ranks_raises_lru_floor_no_self_thrash():
    """A per-rank request whose rank count exceeds max_entries must raise
    the effective LRU bound instead of evicting its own entries — else a
    ks=2..10 sweep against the default cap of 8 would pay one recompile
    on EVERY warm request, forever."""
    ccfg = ConsensusConfig(ks=(2, 3), restarts=2, seed=3,
                           grid_exec="grid", grid_slots=2)
    cache = ExecCache(ExecCacheConfig(pipeline_ranks=True, max_entries=1))
    cache.run_sweep(_A_SMALL, ccfg, _SCFG_TINY, InitConfig())
    assert cache.stats["entries"] == 2  # both ranks stayed resident
    assert cache.evictions == 0
    cache.run_sweep(_A_SMALL, ccfg, _SCFG_TINY, InitConfig())
    assert cache.misses == 2  # the repeat request was fully compile-free


# --- fresh-process cold start (the acceptance contract) -------------------

_FRESH_CHILD = textwrap.dedent("""
    import json, sys
    import numpy as np
    from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                             SolverConfig)
    from nmfx import exec_cache as ec

    a = np.random.default_rng(0).uniform(0.1, 1.0, (60, 20))
    cache = ec.ExecCache(ExecCacheConfig(cache_dir=sys.argv[1]))
    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=3, grid_exec="grid",
                           grid_slots=2)
    out = cache.run_sweep(a, ccfg, SolverConfig(max_iter=20), InitConfig())
    print(json.dumps({
        "compiles": ec.compile_count(),
        "persist_hits": cache.stats["persist_hits"],
        "labels": np.asarray(out[2].labels).tolist(),
        "dnorms": np.asarray(out[2].dnorms).tolist()}))
""")


def _run_fresh_child(tmp_path, cache_dir):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "fresh_child.py"
    script.write_text(_FRESH_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    proc = subprocess.run([sys.executable, str(script), str(cache_dir)],
                          capture_output=True, text=True, env=env,
                          timeout=240)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_fresh_process_zero_compile_with_warm_disk_cache(tmp_path):
    """THE cold-start acceptance contract: with a warm disk cache a
    fresh process's sweep performs ZERO .lower().compile() calls — the
    exec-layer compile counter stays at 0 — and serves results identical
    to the process that compiled."""
    cache_dir = tmp_path / "exec"
    first = _run_fresh_child(tmp_path, cache_dir)
    assert first["compiles"] >= 1 and first["persist_hits"] == 0
    second = _run_fresh_child(tmp_path, cache_dir)
    assert second["compiles"] == 0  # deserialize-and-dispatch only
    assert second["persist_hits"] == 1
    assert second["labels"] == first["labels"]
    assert second["dnorms"] == first["dnorms"]


# --- concurrent serving (ISSUE 6 satellites) ------------------------------

def test_concurrent_executable_access():
    """ISSUE 6 satellite: the serve front-end hits one ExecCache from
    request threads, the scheduler, and background warms at once. Under
    concurrent hammering over two distinct keys, every counter mutation
    must be lock-guarded (hits + misses == calls exactly) and the
    in-flight future registry must keep same-key compiles single-flight:
    exactly ONE compile per distinct key no matter how many threads race
    it, gated on the module compile counter."""
    import threading

    from nmfx import exec_cache as ec

    cache = ExecCache()
    cfgs = [_SCFG_TINY, dataclasses.replace(_SCFG_TINY, max_iter=22)]
    compiles_before = ec.compile_count()
    n_threads, calls = 8, 3
    errors = []

    def worker(tid):
        try:
            for i in range(calls):
                entry, _ = cache.executable((60, 20), _CCFG_TINY,
                                            cfgs[(tid + i) % len(cfgs)])
                assert entry.bucket == cache.bucket_shape(60, 20)
        except Exception as e:  # surfaced after join
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    s = cache.stats
    # single-flight: one compile per distinct key despite 8 racing
    # threads — a second compile would mean the in-flight dedup tore
    assert s["misses"] == len(cfgs)
    assert ec.compile_count() - compiles_before == len(cfgs)
    assert s["hits"] + s["misses"] == n_threads * calls
    assert s["entries"] == len(cfgs)
    assert s["evictions"] == 0


def test_background_warm_failure_surfaces_on_next_request():
    """ISSUE 6 satellite: WarmTask must not swallow a dead worker's
    exception until a join that may never come — a corrupt warm must
    never strand a serve request forever. The failure is recorded
    against its bucket and the NEXT executable()/run_sweep touching that
    bucket warns once and recompiles cleanly in the foreground."""
    cache = ExecCache()
    orig = ExecCache._compile

    def boom(self, *a, **kw):
        raise RuntimeError("injected warm-compile failure")

    ExecCache._compile = boom
    try:
        task = cache.warm([_A_SMALL.shape], _CCFG_TINY, _SCFG_TINY,
                          background=True)
        # the WarmTask join contract still re-raises
        with pytest.raises(RuntimeError, match="injected warm-compile"):
            task.result(timeout=120)
    finally:
        ExecCache._compile = orig
    assert cache.stats["warm_failures"] == 1
    # the next request touching the poisoned bucket: ONE warning, then a
    # clean foreground recompile serving real results
    with pytest.warns(RuntimeWarning, match="background warmup failed"):
        out = cache.run_sweep(_A_SMALL, _CCFG_TINY, _SCFG_TINY,
                              InitConfig())
    assert out[2].labels.shape == (_CCFG_TINY.restarts, _A_SMALL.shape[1])
    assert cache.stats["warm_failures"] == 0  # consumed, not sticky
    assert cache.stats["entries"] == 1
    # the failure does not poison OTHER buckets' requests, and the
    # recompiled bucket serves hits again
    _, hit = cache.executable(_A_SMALL.shape, _CCFG_TINY, _SCFG_TINY)
    assert hit


def test_foreground_warm_failure_raises_without_recording():
    """A synchronous warm() failure surfaces to its caller directly —
    it must NOT also land in the background-failure ledger, or the next
    request touching the bucket would double-report it with a
    misleading 'background warmup failed' warning."""
    cache = ExecCache()
    orig = ExecCache._compile

    def boom(self, *a, **kw):
        raise RuntimeError("injected warm-compile failure")

    ExecCache._compile = boom
    try:
        with pytest.raises(RuntimeError, match="injected warm-compile"):
            cache.warm([_A_SMALL.shape], _CCFG_TINY, _SCFG_TINY,
                       background=False)
    finally:
        ExecCache._compile = orig
    assert cache.stats["warm_failures"] == 0
    # and the recovery path emits no stale-warm warning
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = cache.run_sweep(_A_SMALL, _CCFG_TINY, _SCFG_TINY,
                              InitConfig())
    assert out[2].labels.shape == (_CCFG_TINY.restarts, _A_SMALL.shape[1])


# --- flip-floor threading -------------------------------------------------

def test_flip_floor_override_matches_static_rule():
    """mu_sched(flip_floor=0) must reproduce class_flip_tol=0.0's exact
    reference rule even when cfg says otherwise — the bucketed
    executables rely on the override to carry the TRUE sample count's
    budget past the padded static n."""
    import jax.numpy as jnp

    from nmfx.ops.sched_mu import mu_sched

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0.1, 1.0, (64, 24)), jnp.float32)
    w0 = jnp.asarray(rng.uniform(0.1, 1.0, (6, 64, 3)), jnp.float32)
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, (6, 3, 24)), jnp.float32)
    cfg_loose = SolverConfig(max_iter=300, class_flip_tol=0.5)
    cfg_strict = SolverConfig(max_iter=300, class_flip_tol=0.0)
    forced = mu_sched(a, w0, h0, cfg_loose, slots=3,
                      flip_floor=jnp.asarray(0, jnp.int32))
    strict = mu_sched(a, w0, h0, cfg_strict, slots=3)
    np.testing.assert_array_equal(np.asarray(forced.iterations),
                                  np.asarray(strict.iterations))
    np.testing.assert_array_equal(np.asarray(forced.stop_reason),
                                  np.asarray(strict.stop_reason))


# --- api integration ------------------------------------------------------

def test_nmfconsensus_exec_cache_parity(serve_data):
    from nmfx.api import nmfconsensus

    a, _ = serve_data
    kwargs = dict(ks=(2, 3), restarts=5, seed=11, max_iter=200)
    ref = nmfconsensus(a, **kwargs)
    cache = ExecCache()
    got = nmfconsensus(a, exec_cache=cache, **kwargs)
    assert cache.stats["misses"] == 1  # the sweep really went through it
    for k in (2, 3):
        np.testing.assert_allclose(got.per_k[k].consensus,
                                   ref.per_k[k].consensus, atol=1e-6)
        assert got.per_k[k].rho == ref.per_k[k].rho
        np.testing.assert_array_equal(got.per_k[k].membership,
                                      ref.per_k[k].membership)
    assert got.best_k == ref.best_k


def test_exec_cache_leaves_persistent_cache_config_alone(serve_data):
    """The exec cache must not touch jax's persistent compilation-cache
    config (the conftest cache-reset fixture isolates THAT between
    tests; the serving cache is a separate, in-process layer)."""
    a, _ = serve_data
    before = (jax.config.jax_compilation_cache_dir,
              jax.config.jax_persistent_cache_min_compile_time_secs)
    ExecCache().run_sweep(a, CCFG, SCFG, InitConfig())
    assert (jax.config.jax_compilation_cache_dir,
            jax.config.jax_persistent_cache_min_compile_time_secs) == before


def test_nndsvd_external_init_route(serve_data):
    """NNDSVD requests take the external lane-batch route (the SVD
    factors the true matrix, so init cannot move inside the bucketed
    executable) — results must still match the exact-shape sweep."""
    a, _ = serve_data
    icfg = InitConfig(method="nndsvd")
    ref = sweep(a, CCFG, SCFG, icfg, None)
    cache = ExecCache()
    got = cache.run_sweep(a, CCFG, SCFG, icfg, None)
    for k in CCFG.ks:
        np.testing.assert_array_equal(np.asarray(got[k].labels),
                                      np.asarray(ref[k].labels))
        np.testing.assert_allclose(np.asarray(got[k].consensus),
                                   np.asarray(ref[k].consensus), atol=1e-6)
    # a random-init request under the same sweep config is a DIFFERENT
    # executable (random init is baked in; nndsvd's is external)
    cache.executable(a.shape, CCFG, SCFG, InitConfig())
    assert cache.stats["misses"] == 2
