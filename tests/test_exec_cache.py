"""Executable-reuse serving layer (nmfx/exec_cache.py): bucket policy,
hit/miss keying, LRU eviction, and — the load-bearing property — exact
numerical equivalence of padded-bucket sweeps to exact-shape sweeps."""

import dataclasses

import jax
import numpy as np
import pytest

from nmfx.config import ConsensusConfig, ExecCacheConfig, InitConfig, \
    SolverConfig
from nmfx.exec_cache import ExecCache, bucket_dim, start_host_fetch
from nmfx.sweep import sweep

CCFG = ConsensusConfig(ks=(2, 3), restarts=6, seed=3, grid_exec="grid",
                       grid_slots=4)
SCFG = SolverConfig(max_iter=200)


@pytest.fixture(scope="module")
def serve_data():
    from nmfx.datasets import two_group_matrix

    # two different true shapes that share a bucket under the default
    # lattice (both round up to (256, 64))
    return (two_group_matrix(n_genes=120, n_per_group=12, seed=7),
            two_group_matrix(n_genes=100, n_per_group=10, seed=9))


# --- bucket policy --------------------------------------------------------

def test_bucket_dim_properties():
    for q in (64, 256):
        prev = 0
        for x in (1, q - 1, q, q + 1, 7 * q, 8 * q + 1, 1000, 5000, 99999):
            b = bucket_dim(x, q)
            assert b >= x
            assert b % q == 0
            assert b >= prev or x < prev  # monotonic in x
            # bounded relative padding: the step stops doubling once
            # step·growth_steps >= x, so step <= x/(growth_steps/2)
            assert b <= x * (1 + 2 / 8) + q
            prev = b


def test_bucket_north_star_lands_on_probed_boundary_shape():
    cache = ExecCache()
    # the hardware-probed VMEM boundary shape (bench.py --verify stage 3)
    assert cache.bucket_shape(5000, 500) == (5120, 512)
    assert cache.bucket_shape(4832, 488) == (5120, 512)  # same bucket


def test_bucket_dim_rejects_nonpositive():
    with pytest.raises(ValueError):
        bucket_dim(0, 64)


# --- keying / LRU ---------------------------------------------------------

#: keying/LRU tests compile real executables — keep them tiny (one rank,
#: two restarts) so the suite's compile budget goes to the equivalence
#: tests instead
_CCFG_TINY = ConsensusConfig(ks=(2,), restarts=2, seed=3,
                             grid_exec="grid", grid_slots=2)
_SCFG_TINY = SolverConfig(max_iter=20)


def test_same_bucket_hits_different_config_misses(serve_data):
    a1, a2 = serve_data
    cache = ExecCache()
    cache.executable(a1.shape, _CCFG_TINY, _SCFG_TINY)
    assert cache.stats["misses"] == 1
    _, hit = cache.executable(a2.shape, _CCFG_TINY, _SCFG_TINY)  # same bucket
    assert hit and cache.stats["hits"] == 1
    # any solver-config change re-keys (the config fingerprint)
    _, hit = cache.executable(
        a1.shape, _CCFG_TINY, dataclasses.replace(_SCFG_TINY, max_iter=30))
    assert not hit
    # so does the rank set / restart count / label rule
    _, hit = cache.executable(
        a1.shape, dataclasses.replace(_CCFG_TINY, restarts=3), _SCFG_TINY)
    assert not hit
    assert cache.stats["misses"] == 3


def test_lru_eviction_order():
    cache = ExecCache(ExecCacheConfig(max_entries=2))
    cfgs = [dataclasses.replace(_SCFG_TINY, max_iter=20 + 2 * i)
            for i in range(3)]
    for c in cfgs:
        cache.executable((60, 20), _CCFG_TINY, c)
    assert cache.stats["entries"] == 2
    assert cache.stats["evictions"] == 1
    # evicted: recompile
    _, hit = cache.executable((60, 20), _CCFG_TINY, cfgs[0])
    assert not hit
    _, hit = cache.executable((60, 20), _CCFG_TINY, cfgs[2])  # resident
    assert hit


def test_cacheable_gating():
    cache = ExecCache()
    assert cache.cacheable(CCFG, SCFG, None)
    # pg has no dense-batched block — the scheduler can't run it
    assert not cache.cacheable(CCFG, SolverConfig(algorithm="pg"), None)
    assert not cache.cacheable(
        dataclasses.replace(CCFG, grid_exec="per_k"), SCFG, None)
    with pytest.raises(ValueError):
        cache.run_sweep(np.ones((8, 4)),
                        dataclasses.replace(CCFG, grid_exec="per_k"), SCFG)


# --- padded-bucket numerical equivalence ----------------------------------

@pytest.mark.parametrize("mesh_on", [
    False, pytest.param(True, marks=pytest.mark.slow)])
def test_padded_equivalence_to_exact_sweep(serve_data, mesh_on):
    """The acceptance property: a bucketed sweep (padded A, masked
    consensus, rescaled dnorms, threaded flip budget) must reproduce the
    exact-shape sweep — consensus allclose and identical labels — for
    BOTH true shapes sharing the bucket."""
    from nmfx.sweep import default_mesh

    mesh = default_mesh() if mesh_on else None
    cache = ExecCache()
    icfg = InitConfig()
    for a in serve_data:
        ref = sweep(a, CCFG, SCFG, icfg, mesh)
        got = cache.run_sweep(a, CCFG, SCFG, icfg, mesh)
        for k in CCFG.ks:
            np.testing.assert_array_equal(np.asarray(got[k].labels),
                                          np.asarray(ref[k].labels))
            np.testing.assert_allclose(np.asarray(got[k].consensus),
                                       np.asarray(ref[k].consensus),
                                       atol=1e-6)
            np.testing.assert_allclose(np.asarray(got[k].dnorms),
                                       np.asarray(ref[k].dnorms),
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(got[k].iterations),
                                          np.asarray(ref[k].iterations))
            assert got[k].consensus.shape == (a.shape[1], a.shape[1])
            assert got[k].best_w.shape == (a.shape[0], k)
            assert got[k].best_h.shape == (k, a.shape[1])
    # both shapes served from one executable
    assert cache.stats == {**cache.stats, "entries": 1, "misses": 1,
                           "hits": 1}


def test_keep_factors_unpadded(serve_data):
    a, _ = serve_data
    cache = ExecCache()
    ccfg = dataclasses.replace(CCFG, keep_factors=True)
    out = cache.run_sweep(a, ccfg, SCFG, InitConfig())
    m, n = a.shape
    for k in ccfg.ks:
        assert out[k].all_w.shape == (ccfg.restarts, m, k)
        assert out[k].all_h.shape == (ccfg.restarts, k, n)


def test_prefetch_handle_round_trip(serve_data):
    a, _ = serve_data
    cache = ExecCache()
    placed = cache.prefetch(a, SCFG)
    assert placed.true_shape == a.shape
    assert placed.a_pad.shape == placed.bucket
    out = cache.run_sweep(placed, CCFG, SCFG, InitConfig())
    ref = cache.run_sweep(a, CCFG, SCFG, InitConfig())
    for k in CCFG.ks:
        np.testing.assert_array_equal(np.asarray(out[k].labels),
                                      np.asarray(ref[k].labels))


def test_start_host_fetch_is_safe_everywhere():
    # arrays, Nones, nested pytrees — never raises, never blocks
    import jax.numpy as jnp

    start_host_fetch({"x": jnp.ones((3,)), "y": None,
                      "z": [np.ones(2), jnp.zeros(())]})


def test_threefry_flat_index_properties():
    """The two partitionable-threefry properties the inside-executable
    init (sweep._dyn_lane_init) rests on: draws are counter-based per
    FLAT element index, so (a) same-column-count draws are
    row-prefix-stable and (b) a 1-D draw gathered at i·n_true + j equals
    the true 2-D draw. If a jax upgrade ever breaks these, the bucketed
    executables would silently produce different (still valid, but not
    exact-sweep-equal) restarts — fail here instead."""
    import jax.numpy as jnp

    key = jax.random.key(42)
    wp = jax.random.uniform(key, (1024, 3), jnp.float32, 0.2, 0.9)
    wt = jax.random.uniform(key, (970, 3), jnp.float32, 0.2, 0.9)
    np.testing.assert_array_equal(np.asarray(wp[:970]), np.asarray(wt))
    hu = jax.random.uniform(key, (3 * 256,), jnp.float32, 0.2, 0.9)
    ht = jax.random.uniform(key, (3, 197), jnp.float32, 0.2, 0.9)
    i = jnp.arange(3)[:, None]
    j = jnp.arange(197)[None, :]
    np.testing.assert_array_equal(np.asarray(hu[i * 197 + j]),
                                  np.asarray(ht))


# --- flip-floor threading -------------------------------------------------

def test_flip_floor_override_matches_static_rule():
    """mu_sched(flip_floor=0) must reproduce class_flip_tol=0.0's exact
    reference rule even when cfg says otherwise — the bucketed
    executables rely on the override to carry the TRUE sample count's
    budget past the padded static n."""
    import jax.numpy as jnp

    from nmfx.ops.sched_mu import mu_sched

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(0.1, 1.0, (64, 24)), jnp.float32)
    w0 = jnp.asarray(rng.uniform(0.1, 1.0, (6, 64, 3)), jnp.float32)
    h0 = jnp.asarray(rng.uniform(0.1, 1.0, (6, 3, 24)), jnp.float32)
    cfg_loose = SolverConfig(max_iter=300, class_flip_tol=0.5)
    cfg_strict = SolverConfig(max_iter=300, class_flip_tol=0.0)
    forced = mu_sched(a, w0, h0, cfg_loose, slots=3,
                      flip_floor=jnp.asarray(0, jnp.int32))
    strict = mu_sched(a, w0, h0, cfg_strict, slots=3)
    np.testing.assert_array_equal(np.asarray(forced.iterations),
                                  np.asarray(strict.iterations))
    np.testing.assert_array_equal(np.asarray(forced.stop_reason),
                                  np.asarray(strict.stop_reason))


# --- api integration ------------------------------------------------------

def test_nmfconsensus_exec_cache_parity(serve_data):
    from nmfx.api import nmfconsensus

    a, _ = serve_data
    kwargs = dict(ks=(2, 3), restarts=5, seed=11, max_iter=200)
    ref = nmfconsensus(a, **kwargs)
    cache = ExecCache()
    got = nmfconsensus(a, exec_cache=cache, **kwargs)
    assert cache.stats["misses"] == 1  # the sweep really went through it
    for k in (2, 3):
        np.testing.assert_allclose(got.per_k[k].consensus,
                                   ref.per_k[k].consensus, atol=1e-6)
        assert got.per_k[k].rho == ref.per_k[k].rho
        np.testing.assert_array_equal(got.per_k[k].membership,
                                      ref.per_k[k].membership)
    assert got.best_k == ref.best_k


def test_exec_cache_leaves_persistent_cache_config_alone(serve_data):
    """The exec cache must not touch jax's persistent compilation-cache
    config (the conftest cache-reset fixture isolates THAT between
    tests; the serving cache is a separate, in-process layer)."""
    a, _ = serve_data
    before = (jax.config.jax_compilation_cache_dir,
              jax.config.jax_persistent_cache_min_compile_time_secs)
    ExecCache().run_sweep(a, CCFG, SCFG, InitConfig())
    assert (jax.config.jax_compilation_cache_dir,
            jax.config.jax_persistent_cache_min_compile_time_secs) == before


def test_nndsvd_external_init_route(serve_data):
    """NNDSVD requests take the external lane-batch route (the SVD
    factors the true matrix, so init cannot move inside the bucketed
    executable) — results must still match the exact-shape sweep."""
    a, _ = serve_data
    icfg = InitConfig(method="nndsvd")
    ref = sweep(a, CCFG, SCFG, icfg, None)
    cache = ExecCache()
    got = cache.run_sweep(a, CCFG, SCFG, icfg, None)
    for k in CCFG.ks:
        np.testing.assert_array_equal(np.asarray(got[k].labels),
                                      np.asarray(ref[k].labels))
        np.testing.assert_allclose(np.asarray(got[k].consensus),
                                   np.asarray(ref[k].consensus), atol=1e-6)
    # a random-init request under the same sweep config is a DIFFERENT
    # executable (random init is baked in; nndsvd's is external)
    cache.executable(a.shape, CCFG, SCFG, InitConfig())
    assert cache.stats["misses"] == 2
