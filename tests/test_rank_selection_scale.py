"""Scale validation of the rank-selection stack (VERDICT round-1 item 8):
the native C++ and on-device clustering paths against scipy at large n —
round-1 tests stopped at n ≲ 40.

Consensus matrices quantize to multiples of 1/restarts, so exact distance
ties are abundant at scale; different (all valid) tie resolutions yield
different trees, which would make cross-implementation comparison
meaningless. The fixtures break ties with a tiny symmetric jitter so every
implementation must produce the SAME tree, making the equivalence strict.
"""

import numpy as np
import pytest
import scipy.cluster.hierarchy as sch
import scipy.spatial.distance as ssd

from nmfx import cophenetic as coph
from nmfx import native


def _blocky_consensus(n, groups=4, restarts=30, flip=0.15, seed=5,
                      jitter_scale=1e-9):
    """Planted-group consensus matrix with restart noise + tie-breaking
    jitter: blocky like a real sweep's output, but with a unique tree.
    ``jitter_scale`` must exceed the comparing implementations' relative
    resolution (1e-9 for f64-vs-f64; the f32 device path needs ~1e-4, still
    tiny next to the 1/restarts quantum)."""
    rng = np.random.default_rng(seed)
    true = np.repeat(np.arange(groups), -(-n // groups))[:n]
    labels = np.tile(true, (restarts, 1))
    flips = rng.random((restarts, n)) < flip
    labels[flips] = rng.integers(0, groups, int(flips.sum()))
    cons = (labels[:, :, None] == labels[:, None, :]).mean(0)
    jitter = rng.uniform(0, jitter_scale, (n, n))
    jitter = (jitter + jitter.T) / 2
    np.fill_diagonal(jitter, 0)
    cons = np.clip(cons - jitter, 0.0, 1.0)
    np.fill_diagonal(cons, 1.0)
    return cons


def _pairs(labels):
    """Partition as a pair-connectivity matrix (label-permutation
    invariant)."""
    labels = np.asarray(labels)
    return labels[:, None] == labels[None, :]


@pytest.mark.skipif(not native.available(), reason="native library not built")
def test_native_matches_scipy_at_n2000():
    n, k = 2000, 4
    cons = _blocky_consensus(n)
    dist = 1.0 - cons
    np.fill_diagonal(dist, 0.0)

    z_ours, coph_ours, order = native.average_linkage(dist)
    z_ours = np.asarray(z_ours)
    condensed = ssd.squareform(dist, checks=False)
    z_scipy = sch.linkage(condensed, method="average")

    # same tree: merge heights and cluster sizes agree merge-for-merge
    # (UPGMA heights are monotone, and the jitter makes the order unique)
    np.testing.assert_allclose(z_ours[:, 2], z_scipy[:, 2], rtol=1e-9)
    np.testing.assert_array_equal(z_ours[:, 3], z_scipy[:, 3])
    # cophenetic distances agree with scipy's
    np.testing.assert_allclose(
        ssd.squareform(np.asarray(coph_ours), checks=False),
        sch.cophenet(z_scipy), rtol=1e-9)
    # cut at k: identical partition modulo label permutation
    rho, mem, _ = coph.rank_selection(cons, k, "average")
    mem_scipy = sch.fcluster(z_scipy, t=k, criterion="maxclust")
    np.testing.assert_array_equal(_pairs(mem), _pairs(mem_scipy))
    # cophenetic correlation against a direct scipy computation
    rho_scipy = np.corrcoef(condensed, sch.cophenet(z_scipy))[0, 1]
    assert abs(rho - rho_scipy) < 1e-9
    # leaf order is a valid permutation with contiguous clusters
    assert sorted(np.asarray(order).tolist()) == list(range(n))


@pytest.mark.skipif(not native.available(), reason="native library not built")
def test_native_tie_breaking_matches_numpy_bitwise():
    """Quantized (tie-heavy) distances — the production case, since
    consensus values are multiples of 1/restarts: the native
    nearest-neighbor-cached merge loop must pick the SAME pair as the numpy
    full-rescan at every exact tie (first minimum in row-major order), so
    the linkage tables agree bitwise. This is the test the jittered
    fixtures above deliberately cannot provide."""
    rng = np.random.default_rng(3)
    for trial in range(10):
        n = int(rng.integers(5, 60))
        x = rng.integers(0, 5, size=(n, 3)).astype(float)
        dist = np.abs(x[:, None, :] - x[None, :, :]).sum(-1)
        np.fill_diagonal(dist, 0.0)
        ours = native.average_linkage(dist)
        ref = coph.average_linkage_numpy(dist)
        np.testing.assert_array_equal(np.asarray(ours.linkage), ref.linkage,
                                      err_msg=f"trial {trial} n={n}")
        np.testing.assert_array_equal(np.asarray(ours.coph), ref.coph)
        np.testing.assert_array_equal(np.asarray(ours.order), ref.order)


@pytest.mark.slow
def test_device_matches_host_at_n800():
    """The on-device path at a scale two orders beyond its round-1 tests
    (n=800 keeps the O(n³) fori_loop tractable on the CPU test platform;
    the same comparison at n=2000 on real TPU is recorded in
    benchmarks/RESULTS.md)."""
    import jax.numpy as jnp

    from nmfx.ops.hclust_jax import rank_selection_jax

    n, k = 800, 4
    # f32-visible jitter: the device casts the consensus to f32, where a
    # 1e-9 perturbation vanishes and the quantized ties would reappear
    cons = _blocky_consensus(n, seed=9, jitter_scale=1e-4)
    rho_host, mem_host, order_host = coph.rank_selection(cons, k, "average")
    rho_dev, mem_dev, order_dev = rank_selection_jax(
        jnp.asarray(cons), k, "average")
    # identical tree is the strict check; rho then differs only by f32
    # accumulation over the n(n-1)/2-pair correlation
    np.testing.assert_array_equal(np.asarray(mem_dev), mem_host)
    np.testing.assert_array_equal(np.asarray(order_dev), order_host)
    assert abs(float(rho_dev) - rho_host) < 1e-3
