"""Resilient service tier (ISSUE 15): replica pool + health-checked
router — placement, failover, spill-migration, SLO shedding.

Queue/placement/failover mechanics run against thread replicas whose
servers use the scriptable :class:`test_serve.FakeEngine` (milliseconds,
no device dispatch); the claim protocol is unit-tested directly; the
cross-process half (subprocess workers, SIGKILL recovery) lives in the
slow-marked process tests here plus the two-process claim race in
tests/test_multiprocess.py and the bench ``detail.serve.fleet`` chaos
rung. The stale-heartbeat eviction test is watchdog-bounded: every
``result()`` carries a timeout, so a hang is a failure, never a stuck
suite."""

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from test_serve import FakeEngine, _mat

import nmfx.serve as serve
from nmfx import faults
from nmfx.replica import ReplicaPool, SpawnFailed
from nmfx.router import (ForwardFailed, NMFXRouter, NoRoutableReplicas,
                         RouterClosed, RouterConfig, RouterOverloaded)
from nmfx.serve import ServeConfig


def _fast_cfg(**kw) -> RouterConfig:
    base = dict(retry_backoff_s=0.01, health_interval_s=0.03)
    base.update(kw)
    return RouterConfig(**base)


def _pool(tmp_path, n=2, engine_factory=FakeEngine, **kw):
    kw.setdefault("heartbeat_interval_s", 0.05)
    return ReplicaPool(n, root=str(tmp_path / "pool"), mode="thread",
                       engine_factory=engine_factory, **kw)


def _sticky_id(router, arr) -> str:
    """Which replica the router's rendezvous hash prefers for this
    matrix — computable by tests because the placement is
    deterministic in (content hash, replica id)."""
    chash = hashlib.sha256(np.asarray(arr).tobytes()).hexdigest()
    ids = [rep.replica_id for rep in router.pool.routable()]
    return max(ids, key=lambda rid: NMFXRouter._hrw(chash, rid))


# ---------------------------------------------------------------------
# config + basic forwarding
# ---------------------------------------------------------------------

def test_router_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(max_outstanding=0)
    with pytest.raises(ValueError):
        RouterConfig(forward_retries=-1)
    with pytest.raises(ValueError):
        RouterConfig(forward_timeout_s=0.0)
    with pytest.raises(ValueError):
        RouterConfig(stale_after_s=0.0)
    with pytest.raises(ValueError):
        RouterConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        RouterConfig(stickiness_slack=-1)


def test_pool_validation(tmp_path):
    with pytest.raises(ValueError):
        ReplicaPool(0, root=str(tmp_path / "p"))
    with pytest.raises(ValueError):
        ReplicaPool(1, root=str(tmp_path / "p"), mode="carrier-pigeon")
    with pytest.raises(ValueError):
        ReplicaPool(1, root=str(tmp_path / "p"), mode="process",
                    engine_factory=FakeEngine)


def test_basic_forward_resolves_with_stats(tmp_path):
    with NMFXRouter(_pool(tmp_path), _fast_cfg()) as router:
        fut = router.submit(_mat(), ks=(2,), restarts=2, seed=7)
        res = fut.result(timeout=60)
    assert res.per_k[2].consensus is not None
    st = fut.stats
    assert st.request_id and st.replica and st.attempts == 1
    assert st.sticky is True and st.latency_s is not None
    assert st.retried == []
    s = router.stats()
    assert s["submitted"] == 1 and s["completed"] == 1
    assert s["failed"] == 0 and s["outstanding"] == 0


def test_content_hash_stickiness_is_deterministic(tmp_path):
    """Repeat submissions of one matrix land on ONE replica (the
    rendezvous choice, predictable from content hash + ids), so its
    device-resident input cache actually hits."""
    with NMFXRouter(_pool(tmp_path, n=3), _fast_cfg()) as router:
        a = _mat()
        want = _sticky_id(router, a)
        for seed in range(4):
            f = router.submit(a, ks=(2,), restarts=2, seed=seed)
            f.result(timeout=60)
            assert f.stats.replica == want


def test_stickiness_breaks_to_least_loaded(tmp_path):
    """A loaded sticky replica yields: with slack 0, the second
    concurrent request on the same matrix routes to the idle
    replica."""
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg(stickiness_slack=0)) as router:
        a = _mat()
        sticky = _sticky_id(router, a)
        for rep in pool.routable():
            rep.server.pause()  # queue everything deterministically
        f1 = router.submit(a, ks=(2,), restarts=2, seed=1)
        f2 = router.submit(a, ks=(2,), restarts=2, seed=2)
        assert f1.stats.replica == sticky
        assert f2.stats.replica != sticky
        assert f2.stats.sticky is False
        for rep in pool.routable():
            rep.server.resume()
        f1.result(timeout=60)
        f2.result(timeout=60)


# ---------------------------------------------------------------------
# failover: retry on another replica, typed exhaustion, fault site
# ---------------------------------------------------------------------

class _BoomEngine(FakeEngine):
    """Every dispatch fails — the replica's server exhausts its own
    retries and resolves RequestFailed, the router's retryable cue."""

    def __init__(self):
        super().__init__(compat=None)

    def dispatch_solo(self, req, placed, scfg):
        raise RuntimeError("boom")

    def dispatch_packed(self, reqs, placed):
        raise RuntimeError("boom")


def _pool_with_bad_sticky(tmp_path, arr, n=2):
    """A pool where the replica STICKY for ``arr`` fails every
    dispatch and the others serve normally — deterministic because
    replica ids (and hence the rendezvous choice) are known up
    front."""
    pid = os.getpid()
    ids = [f"replica-{pid}-{i}" for i in range(n)]
    chash = hashlib.sha256(np.asarray(arr).tobytes()).hexdigest()
    bad = max(ids, key=lambda rid: NMFXRouter._hrw(chash, rid))
    made = {}

    def factory():
        # spawn order matches the id sequence
        rid = ids[len(made)]
        eng = _BoomEngine() if rid == bad else FakeEngine(compat=None)
        made[rid] = eng
        return eng

    pool = ReplicaPool(n, root=str(tmp_path / "pool"), mode="thread",
                       engine_factory=factory,
                       serve_cfg=ServeConfig(dispatch_retries=0),
                       heartbeat_interval_s=0.05)
    assert list(made) == ids
    return pool, bad, made


def test_retry_on_another_replica(tmp_path):
    a = _mat()
    pool, bad, engines = _pool_with_bad_sticky(tmp_path, a)
    with NMFXRouter(pool, _fast_cfg()) as router:
        fut = router.submit(a, ks=(2,), restarts=2, seed=5)
        res = fut.result(timeout=60)
    assert res is not None
    assert fut.stats.attempts == 2
    assert fut.stats.replica != bad
    assert fut.stats.retried == ["RequestFailed"]
    assert router.stats()["retried"] == 1


def test_forward_exhaustion_resolves_typed(tmp_path):
    pool = _pool(tmp_path, engine_factory=_BoomEngine,
                 serve_cfg=ServeConfig(dispatch_retries=0))
    with NMFXRouter(pool, _fast_cfg(forward_retries=1)) as router:
        fut = router.submit(_mat(), ks=(2,), restarts=2)
        with pytest.raises(ForwardFailed) as ei:
            fut.result(timeout=60)
    assert isinstance(ei.value.__cause__, serve.RequestFailed)
    assert fut.stats.attempts == 2  # initial + 1 re-forward


def test_router_forward_fault_site_retries(tmp_path):
    """The armed ``router.forward`` chaos site fails the first forward;
    the request recovers on the retry and the fire lands on the flight
    recorder (NMFX008 coverage end-to-end)."""
    from nmfx.obs import flight

    with NMFXRouter(_pool(tmp_path), _fast_cfg()) as router:
        with faults.scoped("router.forward", every=1, max_fires=1):
            fut = router.submit(_mat(), ks=(2,), restarts=2)
            fut.result(timeout=60)
            assert faults.fires("router.forward") == 1
    assert fut.stats.attempts == 2
    assert fut.stats.retried == ["FaultInjected"]
    fires = flight.default_recorder().events("fault.router.forward")
    assert fires and fires[-1]["site"] == "router.forward"


def test_queue_full_fails_over(tmp_path):
    """A replica at its admission bound raises QueueFull at forward
    time; the router immediately places the request elsewhere."""
    a = _mat()
    pool = _pool(tmp_path,
                 serve_cfg=ServeConfig(max_queue_depth=1))
    with NMFXRouter(pool, _fast_cfg(stickiness_slack=5)) as router:
        sticky = _sticky_id(router, a)
        pool.get(sticky).server.pause()
        f1 = router.submit(a, ks=(2,), restarts=2, seed=1)  # fills it
        f2 = router.submit(a, ks=(2,), restarts=2, seed=2)
        assert f2.stats.replica != sticky
        assert f2.stats.retried == ["QueueFull"]
        f2.result(timeout=60)
        pool.get(sticky).server.resume()
        f1.result(timeout=60)


def test_no_routable_replicas_typed(tmp_path):
    with NMFXRouter(_pool(tmp_path, n=1), _fast_cfg()) as router:
        router.drain_replica(next(iter(router.pool.replicas)))
        with pytest.raises(NoRoutableReplicas):
            router.submit(_mat(), ks=(2,), restarts=2)


# ---------------------------------------------------------------------
# at-most-once dispatch
# ---------------------------------------------------------------------

def test_forward_timeout_waits_for_dispatched_request(tmp_path):
    """A forward that already DISPATCHED is never re-placed on a live
    replica: at-most-once dispatch beats tail latency. One engine
    dispatch total, one delivery."""
    eng_holder = []

    def factory():
        eng = FakeEngine(compat=None, delay=0.6)
        eng_holder.append(eng)
        return eng

    pool = _pool(tmp_path, engine_factory=factory)
    with NMFXRouter(pool,
                    _fast_cfg(forward_timeout_s=0.1)) as router:
        fut = router.submit(_mat(), ks=(2,), restarts=2)
        res = fut.result(timeout=60)
    assert res is not None
    assert fut.stats.attempts == 1
    assert sum(len(e.solo) for e in eng_holder) == 1


def test_forward_timeout_replaces_undispatched(tmp_path):
    """A forward still QUEUED at timeout provably never dispatched
    (the cancel succeeds) — re-placing it elsewhere is safe and the
    router does so."""
    a = _mat()
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg(forward_timeout_s=0.1)) as router:
        sticky = _sticky_id(router, a)
        pool.get(sticky).server.pause()
        fut = router.submit(a, ks=(2,), restarts=2)
        res = fut.result(timeout=60)
        pool.get(sticky).server.resume()
    assert res is not None
    assert fut.stats.replica != sticky
    assert fut.stats.retried == ["TimeoutError"]


# ---------------------------------------------------------------------
# drain + stale-heartbeat eviction (the ISSUE 15 satellite)
# ---------------------------------------------------------------------

def test_drain_migrates_queued_requests(tmp_path):
    """drain_replica: queued requests spill, the router claims each
    record and re-forwards on the survivor — every future resolves,
    no spill record is left behind."""
    a = _mat()
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg()) as router:
        sticky = _sticky_id(router, a)
        victim = pool.get(sticky)
        victim.server.pause()
        futs = [router.submit(a, ks=(2,), restarts=2, seed=i)
                for i in range(3)]
        assert all(f.stats.replica == sticky for f in futs)
        router.drain_replica(sticky)
        for f in futs:
            assert f.result(timeout=60) is not None
            assert f.stats.replica != sticky
            assert f.stats.retried == ["ServerClosed"]
        s = router.stats()
        assert s["drained"] == 1 and s["readmitted"] == 3
        assert sticky not in [r.replica_id for r in pool.routable()]
        assert os.listdir(victim.spill_dir) == []
        # the drained replica's beater stopped: its heartbeat must AGE
        # into staleness, not keep publishing a phantom live instance
        assert victim._beater._thread is None


def test_stale_heartbeat_eviction(tmp_path):
    """The satellite contract: a replica whose heartbeat publisher
    freezes (the armed ``replica.heartbeat`` site) is drained by the
    health checker and its queued requests land on a survivor with
    typed causes on their stats — never a hang (every wait is
    timeout-bounded)."""
    a = _mat()
    pool = _pool(tmp_path)
    router = NMFXRouter(pool, _fast_cfg(stale_after_s=0.3,
                                        health_interval_s=0.03))
    try:
        sticky = _sticky_id(router, a)
        victim = pool.get(sticky)
        survivor = next(rep for rep in pool.routable()
                        if rep.replica_id != sticky)
        victim.server.pause()
        futs = [router.submit(a, ks=(2,), restarts=2, seed=i)
                for i in range(3)]
        assert all(f.stats.replica == sticky for f in futs)
        # the survivor's beater is replaced by direct ledger writes so
        # the armed site freezes ONLY the victim's publisher (arming
        # is process-global; the test needs one frozen, one fresh)
        survivor._beater.close()
        stop = threading.Event()

        def keep_fresh():
            while not stop.is_set():
                pool.ledger.beat(survivor.replica_id, role="replica",
                                 state="routable")
                time.sleep(0.03)

        fresh = threading.Thread(target=keep_fresh, daemon=True)
        fresh.start()
        try:
            with faults.scoped("replica.heartbeat", every=1):
                results = [f.result(timeout=60) for f in futs]
                assert faults.fires("replica.heartbeat") >= 1
        finally:
            stop.set()
            fresh.join()
        assert all(r is not None for r in results)
        for f in futs:
            assert f.stats.replica == survivor.replica_id
            assert f.stats.retried == ["ServerClosed"]  # typed cause
        s = router.stats()
        assert s["drained"] == 1 and s["readmitted"] == 3
    finally:
        router.close()


# ---------------------------------------------------------------------
# deadlines, admission, close
# ---------------------------------------------------------------------

def test_deadline_enforced_at_router(tmp_path):
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg()) as router:
        for rep in pool.routable():
            rep.server.pause()
        fut = router.submit(_mat(), ks=(2,), restarts=2, timeout=0.05)
        with pytest.raises(serve.DeadlineExceeded):
            fut.result(timeout=60)
        for rep in pool.routable():
            rep.server.resume()
    assert router.stats()["outstanding"] == 0


def test_admission_bound_sheds_typed(tmp_path):
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg(max_outstanding=1)) as router:
        for rep in pool.routable():
            rep.server.pause()
        f1 = router.submit(_mat(), ks=(2,), restarts=2)
        with pytest.raises(RouterOverloaded):
            router.submit(_mat(), ks=(2,), restarts=2)
        assert router.stats()["shed"] == 1
        for rep in pool.routable():
            rep.server.resume()
        f1.result(timeout=60)


def test_closed_router_rejects(tmp_path):
    router = NMFXRouter(_pool(tmp_path), _fast_cfg())
    router.close()
    with pytest.raises(RouterClosed):
        router.submit(_mat(), ks=(2,), restarts=2)


def test_close_cancel_pending_resolves_typed(tmp_path):
    pool = _pool(tmp_path)
    router = NMFXRouter(pool, _fast_cfg())
    for rep in pool.routable():
        rep.server.pause()
    fut = router.submit(_mat(), ks=(2,), restarts=2)
    router.close(cancel_pending=True)
    with pytest.raises(RouterClosed):
        fut.result(timeout=60)


# ---------------------------------------------------------------------
# SLO-driven shedding + quality degradation
# ---------------------------------------------------------------------

class _BurnStub:
    """Scriptable SLO engine: reports the given objectives in fast
    burn."""

    def __init__(self, burning=()):
        self.burning = list(burning)
        self._last = None

    def evaluate(self, now=None):
        objs = {name: {"state": ("fast_burn" if name in self.burning
                                 else "ok"), "burn": {}}
                for name in ("availability", "latency_p99")}
        self._last = {"t": 0.0, "objectives": objs,
                      "alerting": list(self.burning)}
        return self._last

    def status(self):
        return self._last


def test_slo_burn_sheds(tmp_path):
    stub = _BurnStub(burning=["availability"])
    with NMFXRouter(_pool(tmp_path),
                    _fast_cfg(shed_on_burn=True, slo_interval_s=0.01),
                    slo_engine=stub) as router:
        router._last_slo = 0.0
        router._check_slo()
        with pytest.raises(RouterOverloaded, match="fast burn"):
            router.submit(_mat(), ks=(2,), restarts=2)
        assert router.stats()["shed"] == 1
        # the burn clears -> submissions flow again
        stub.burning = []
        router._last_slo = 0.0
        router._check_slo()
        router.submit(_mat(), ks=(2,), restarts=2).result(timeout=60)


def test_slo_burn_quality_elastic_degrades_tagged(tmp_path):
    """With quality_elastic, burn-shed requests are served by the
    sketched engine instead of rejected — and the degradation is
    TAGGED end-to-end (stats cause + the engine actually receiving
    backend='sketched'), never silent."""
    stub = _BurnStub(burning=["latency_p99"])
    engines = []

    def factory():
        eng = FakeEngine(compat=None)
        engines.append(eng)
        return eng

    pool = _pool(tmp_path, engine_factory=factory)
    with NMFXRouter(pool,
                    _fast_cfg(shed_on_burn=True, quality_elastic=True,
                              slo_interval_s=0.01),
                    slo_engine=stub) as router:
        router._check_slo()
        fut = router.submit(_mat(), ks=(2,), restarts=2)
        res = fut.result(timeout=60)
    assert fut.stats.degraded_cause == "slo_burn"
    assert res.quality == "sketched"
    dispatched = [scfg for eng in engines for _, scfg in eng.solo]
    assert len(dispatched) == 1
    assert dispatched[0].backend == "sketched"
    assert router.stats()["degraded"] == 1


# ---------------------------------------------------------------------
# elasticity: scale up/down, autoscale, spawn fault
# ---------------------------------------------------------------------

def test_scale_up_and_down(tmp_path):
    pool = _pool(tmp_path, n=1)
    with NMFXRouter(pool, _fast_cfg(min_replicas=1,
                                    max_replicas=3)) as router:
        assert len(pool.routable()) == 1
        rep = router.scale_up()
        assert rep is not None and len(pool.routable()) == 2
        # scale-down drains the least-loaded and migrates nothing
        # (idle) — the pool shrinks back
        assert router.scale_down() is True
        assert len(pool.routable()) == 1
        # refuses below min_replicas
        assert router.scale_down() is False


def test_scale_down_migrates_via_spill(tmp_path):
    """Scale-down of a replica with queued work is a DRAIN: the queued
    requests spill-migrate to a survivor and still resolve."""
    a = _mat()
    pool = _pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg()) as router:
        sticky = _sticky_id(router, a)
        pool.get(sticky).server.pause()
        futs = [router.submit(a, ks=(2,), restarts=2, seed=i)
                for i in range(2)]
        assert router.scale_down(sticky) is True
        for f in futs:
            assert f.result(timeout=60) is not None
            assert f.stats.replica != sticky


def test_spawn_fault_degrades_warn_once(tmp_path):
    pool = _pool(tmp_path, n=1)
    with NMFXRouter(pool, _fast_cfg()) as router:
        with faults.scoped("replica.spawn", every=1):
            with pytest.raises(SpawnFailed):
                pool.spawn()
            assert router.scale_up() is None  # degrades, no raise
        assert len(pool.routable()) == 1
        assert router.scale_up() is not None  # disarmed: works again


def test_autoscale_tick_scales_on_load_and_burn(tmp_path):
    pool = _pool(tmp_path, n=1)
    with NMFXRouter(pool,
                    _fast_cfg(scale_up_outstanding=2.0,
                              max_replicas=3)) as router:
        for rep in pool.routable():
            rep.server.pause()
        futs = [router.submit(_mat(), ks=(2,), restarts=2, seed=i)
                for i in range(2)]
        router.autoscale_tick()  # 2 outstanding >= 2.0 * 1 replica
        assert len(pool.routable()) == 2
        for rep in pool.routable():
            rep.server.resume()
        for f in futs:
            f.result(timeout=60)
        # burn also triggers scale-up regardless of load
        with router._lock:
            router._burning = ["availability"]
        router.autoscale_tick()
        assert len(pool.routable()) == 3


# ---------------------------------------------------------------------
# the spill claim protocol (serve.py satellite)
# ---------------------------------------------------------------------

def _record(tmp_path, name="spill_x.npz"):
    from nmfx.config import InitConfig, SolverConfig

    meta = serve.spill_meta(request_id="x", ks=(2,), restarts=2,
                            seed=1, scfg=SolverConfig(),
                            icfg=InitConfig(), col_names=("a", "b"))
    return serve.write_spill_record(str(tmp_path / name),
                                    np.ones((3, 2)), meta)


def test_claim_is_exclusive_and_releasable(tmp_path):
    p = _record(tmp_path)
    assert serve.claim_spill(p, "a")
    assert not serve.claim_spill(p, "b")
    assert serve.spill_claimant(p)["claimant"] == "a"
    serve.release_spill_claim(p)
    assert serve.spill_claimant(p) is None
    assert serve.claim_spill(p, "b")


def test_break_claim_by_pid_and_age(tmp_path):
    p = _record(tmp_path)
    assert serve.claim_spill(p, "a")
    # live claim, wrong pid, fresh: unbreakable
    assert not serve.break_spill_claim(p, owner_pid=1)
    assert not serve.break_spill_claim(p, older_than_s=3600)
    # matching owner pid: breakable
    assert serve.break_spill_claim(p, owner_pid=os.getpid())
    assert serve.claim_spill(p, "b")
    # age: breakable once provably stale
    assert serve.break_spill_claim(p, older_than_s=0.0)
    assert serve.claim_spill(p, "c")


def test_concurrent_breakers_yield_one_owner(tmp_path):
    """Two threads racing break+reclaim of one stale claim: the
    ``.break`` marker serializes the break, so exactly one ends up
    owning the record — never both (the double-readmission TOCTOU)."""
    import json

    p = _record(tmp_path)
    with open(p + ".claim", "w") as f:
        json.dump({"claimant": "dead", "pid": 999999, "time": 1.0}, f)
    winners = []
    barrier = threading.Barrier(2)

    def contend(who):
        barrier.wait()
        for _ in range(50):
            if serve.break_spill_claim(p, older_than_s=60.0) \
                    and serve.claim_spill(p, who):
                winners.append(who)
                return

    threads = [threading.Thread(target=contend, args=(w,))
               for w in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(winners) == 1
    assert serve.spill_claimant(p)["claimant"] == winners[0]
    assert not os.path.exists(p + ".break")  # marker released


def test_readmit_skips_claimed_records(tmp_path):
    """Two consumers over one spill dir partition it: a record claimed
    by someone else is NOT readmitted (the race-fix satellite;
    tests/test_multiprocess.py races two real processes over it)."""
    eng = FakeEngine()
    spill = tmp_path / "spill"
    spill.mkdir()
    p1 = _record(spill, "spill_1.npz")
    _record(spill, "spill_2.npz")
    assert serve.claim_spill(p1, "someone-else")
    srv = serve.NMFXServer(ServeConfig(spill_dir=str(spill)),
                           engine=eng)
    futs = srv.readmit()
    assert len(futs) == 1
    futs[0].result(timeout=60)
    srv.close()
    assert os.path.exists(p1)  # the claimed record stayed put
    assert serve.spill_claimant(p1)["claimant"] == "someone-else"


def test_readmit_breaks_stale_claims_on_request(tmp_path):
    import json

    eng = FakeEngine()
    spill = tmp_path / "spill"
    spill.mkdir()
    p1 = _record(spill, "spill_1.npz")
    # a claim whose owner died long ago (embedded time far in the past)
    with open(p1 + ".claim", "w") as f:
        json.dump({"claimant": "dead", "pid": 999999, "time": 1.0}, f)
    srv = serve.NMFXServer(ServeConfig(spill_dir=str(spill)),
                           engine=eng)
    assert srv.readmit() == []  # default: never break
    futs = srv.readmit(break_claims_after_s=60.0)
    assert len(futs) == 1
    futs[0].result(timeout=60)
    srv.close()
    assert not os.path.exists(p1)


def test_readmit_cleans_orphan_claims(tmp_path):
    eng = FakeEngine()
    spill = tmp_path / "spill"
    spill.mkdir()
    # an orphan claim: its record was already admitted by a consumer
    # that died before releasing
    orphan = str(spill / "spill_gone.npz")
    assert serve.claim_spill(orphan, "dead-consumer")
    srv = serve.NMFXServer(ServeConfig(spill_dir=str(spill)),
                           engine=eng)
    srv.readmit()
    srv.close()
    assert os.listdir(spill) == []


# ---------------------------------------------------------------------
# process replicas: the subprocess worker transport + SIGKILL recovery
# ---------------------------------------------------------------------

def _worker_env():
    """Subprocess replicas must match the parent's virtual-device
    platform (conftest forces 8 CPU devices via jax.config, which
    children cannot inherit) — same platform, same GEMM partitioning,
    same bits (the PR 13 fixed-geometry contract is per-platform)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _assert_bit_equal(got, ref):
    for k in ref.per_k:
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            assert np.array_equal(
                np.asarray(getattr(got.per_k[k], field)),
                np.asarray(getattr(ref.per_k[k], field))), \
                f"{field} k={k}"
        assert got.per_k[k].rho == ref.per_k[k].rho


def test_process_replica_serves_bit_identical(tmp_path):
    """One subprocess worker end to end: the spill-record transport +
    claim protocol + outbox result path deliver bit-identical results
    to a solo run through the same serving layer."""
    from nmfx.api import nmfconsensus
    from nmfx.config import SolverConfig
    from nmfx.datasets import two_group_matrix
    from nmfx.exec_cache import ExecCache

    a = two_group_matrix(n_genes=60, n_per_group=10, seed=3)
    scfg = SolverConfig(max_iter=30)
    pool = ReplicaPool(1, root=str(tmp_path / "pool"), mode="process",
                       env=_worker_env())
    with NMFXRouter(pool, _fast_cfg()) as router:
        fut = router.submit(a, ks=(2,), restarts=2, seed=11,
                            solver_cfg=scfg)
        res = fut.result(timeout=180)
    ref = nmfconsensus(a, ks=(2,), restarts=2, seed=11,
                       solver_cfg=scfg, use_mesh=False,
                       exec_cache=ExecCache())
    _assert_bit_equal(res, ref)
    # the transport cleaned up after itself
    rep = next(iter(pool.replicas.values()))
    assert os.listdir(rep.inbox) == []
    assert os.listdir(rep.outbox) == []


def test_sigkilled_process_replica_recovers_bit_identical(tmp_path):
    """The acceptance chaos shape: one of two subprocess replicas is
    SIGKILLed with requests outstanding; the router reclaims its
    write-ahead inbox records (breaking the dead pid's claims) and
    readmits on the survivor — every future resolves, results
    bit-identical to an uninterrupted solo run."""
    from nmfx.api import nmfconsensus
    from nmfx.config import SolverConfig
    from nmfx.datasets import two_group_matrix
    from nmfx.exec_cache import ExecCache

    a = two_group_matrix(n_genes=60, n_per_group=10, seed=3)
    scfg = SolverConfig(max_iter=30)
    pool = ReplicaPool(2, root=str(tmp_path / "pool"), mode="process",
                       env=_worker_env())
    with NMFXRouter(pool, _fast_cfg(stickiness_slack=8)) as router:
        victim_id = _sticky_id(router, a)
        victim = pool.get(victim_id)
        futs = [router.submit(a, ks=(2,), restarts=2, seed=s,
                              solver_cfg=scfg)
                for s in (11, 12, 13)]
        assert all(f.stats.replica == victim_id for f in futs)
        victim.kill()
        results = [f.result(timeout=180) for f in futs]
    cache = ExecCache()
    for seed, (f, res) in zip((11, 12, 13), zip(futs, results)):
        ref = nmfconsensus(a, ks=(2,), restarts=2, seed=seed,
                           solver_cfg=scfg, use_mesh=False,
                           exec_cache=cache)
        _assert_bit_equal(res, ref)
    s = router.stats()
    assert s["recovered"] == 1 and s["readmitted"] >= 1
    assert s["completed"] == 3 and s["failed"] == 0


# ---------------------------------------------------------------------
# fleet view: router + replica roles render distinctly
# ---------------------------------------------------------------------

def test_top_renders_roles_distinctly(tmp_path):
    from nmfx.obs.aggregate import FleetCollector
    from nmfx.obs.export import TelemetryPublisher
    from nmfx.obs.slo import SLOEngine
    from nmfx.obs.top import gather, render_html, render_text

    tdir = str(tmp_path / "telemetry")
    TelemetryPublisher(tdir, role="router",
                       instance="router-0").publish_once()
    TelemetryPublisher(
        tdir, role="replica", instance="replica-0",
        status_fn=lambda: {"queue_depth": 5,
                           "inflight": 1}).publish_once()
    collector = FleetCollector(tdir, stale_after_s=30.0)
    rows = collector.instances()
    by_role = {r["role"]: r for r in rows}
    assert set(by_role) == {"router", "replica"}
    # the payload-embedded status reaches the instance row
    assert by_role["replica"]["queue_depth"] == 5
    assert by_role["replica"]["inflight"] == 1
    frame = gather(collector,
                   SLOEngine(snapshot_fn=collector.fleet_snapshot))
    text = render_text(frame, tdir)
    assert "roles:" in text
    assert "replica 1 live" in text and "router 1 live" in text
    html = render_html(frame, tdir)
    assert "replica 1 live" in html and "router 1 live" in html


def test_replica_heartbeats_carry_levels(tmp_path):
    """Pool replicas publish queue-depth/inflight into the shared
    ledger — the load row the router health checker and nmfx-top
    read."""
    from nmfx.config import InitConfig, SolverConfig

    pool = _pool(tmp_path, n=1)
    try:
        rep = pool.routable()[0]
        rep.server.pause()
        a = np.asarray(_mat())
        meta = serve.spill_meta(
            request_id="rid-x", ks=(2,), restarts=2, seed=1,
            scfg=SolverConfig(), icfg=InitConfig(),
            col_names=[str(i) for i in range(a.shape[1])])
        fut = rep.forward("rid-x", a, meta)
        rep._beater.beat_once()
        hb = pool.heartbeats(stale_after_s=30.0)[rep.replica_id]
        assert hb["role"] == "replica" and hb["queue_depth"] == 1
        assert hb["stale"] is False
        rep.server.resume()
        fut.result(timeout=60)
    finally:
        pool.close()


# ---------------------------------------------------------------------
# priced placement over a heterogeneous fleet (ISSUE 19)
# ---------------------------------------------------------------------

def _hetero_pool(tmp_path, **kw):
    """One plain 1-chip replica + one 4-chip mesh replica (FakeEngine —
    placement mechanics only, no device dispatch)."""
    return _pool(tmp_path, n=2, mesh_specs=(None, "4"), **kw)


def _placement_count(klass) -> float:
    from nmfx.obs import metrics as obs_metrics

    rec = obs_metrics.registry().snapshot().get(
        "nmfx_router_placement_total")
    if not rec:
        return 0.0
    return float(rec["series"].get((str(klass),), 0.0))


def test_atlas_floor_validation():
    with pytest.raises(ValueError):
        RouterConfig(atlas_floor_bytes=0)


def test_heartbeats_advertise_mesh_class(tmp_path):
    pool = _hetero_pool(tmp_path)
    try:
        classes = sorted((str(r.mesh_spec), r.n_devices)
                         for r in pool.routable())
        assert classes == [("4", 4), ("None", 1)]
        # the heartbeat ledger carries the same capability facts — the
        # router prices off these fields cross-process
        for rep in pool.routable():
            rep._beater.beat_once()
        beats = sorted((str(hb.get("mesh")), hb.get("devices"))
                       for hb in pool.heartbeats().values())
        assert beats == classes
    finally:
        pool.close()


def test_priced_placement_small_vs_atlas(tmp_path):
    """The acceptance gate: an atlas-shaped request must NEVER land on
    a 1-chip replica while a mesh replica is routable — and small
    requests must not squat the mesh."""
    small = _mat()                        # 8x6 f32 = 192 B
    atlas = np.asarray(_mat(n=32, m=64))  # 8 KiB
    floor = small.nbytes + 1
    pool = _hetero_pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg(atlas_floor_bytes=floor)) as router:
        c1, c4 = _placement_count(1), _placement_count(4)
        for _ in range(3):
            fut = router.submit(atlas, ks=(2,), restarts=2)
            fut.result(timeout=60)
            assert fut.stats.placement_class == 4
            inputs = fut.stats.placement_inputs
            assert inputs["atlas"] is True
            assert inputs["bytes"] == atlas.nbytes
            assert inputs["classes"] == [1, 4]
            assert "queue_depth" in inputs
        for _ in range(3):
            fut = router.submit(small, ks=(2,), restarts=2)
            fut.result(timeout=60)
            assert fut.stats.placement_class == 1
            assert fut.stats.placement_inputs["atlas"] is False
        assert _placement_count(4) - c4 == 3
        assert _placement_count(1) - c1 == 3


def test_pricing_off_leaves_stats_unpriced(tmp_path):
    """price_placement=False drops the class FILTER (any replica may
    win) and the decision-inputs audit; the landed class is still
    recorded — it is telemetry, not policy."""
    pool = _hetero_pool(tmp_path)
    with NMFXRouter(pool, _fast_cfg(price_placement=False)) as router:
        fut = router.submit(_mat(), ks=(2,), restarts=2)
        fut.result(timeout=60)
        assert fut.stats.placement_class in (1, 4)
        assert fut.stats.placement_inputs is None


def test_atlas_falls_back_when_mesh_unroutable(tmp_path):
    """Pricing is a preference, not an admission gate: with the mesh
    replica down, atlas requests still flow to the 1-chip replica."""
    pool = _hetero_pool(tmp_path)
    atlas = np.asarray(_mat(n=32, m=64))
    with NMFXRouter(pool, _fast_cfg(atlas_floor_bytes=1)) as router:
        meshed = [r for r in pool.routable() if r.n_devices == 4][0]
        meshed.drain()
        deadline = time.time() + 10
        while any(r.n_devices == 4 for r in pool.routable()):
            if time.time() > deadline:
                pytest.fail("mesh replica never left the routable set")
            time.sleep(0.05)
        fut = router.submit(atlas, ks=(2,), restarts=2)
        fut.result(timeout=60)
        assert fut.stats.placement_class == 1


def test_pool_mesh_specs_validation(tmp_path):
    from nmfx.distributed import MeshSpecError

    with pytest.raises(ValueError, match="mesh_specs has 1"):
        ReplicaPool(2, root=str(tmp_path / "p1"), mode="thread",
                    engine_factory=FakeEngine, mesh_specs=("4",))
    with pytest.raises(MeshSpecError):
        ReplicaPool(1, root=str(tmp_path / "p2"), mode="thread",
                    engine_factory=FakeEngine, mesh_specs=("zero",))
