"""The random-projection compressed engine (``backend="sketched"``,
nmfx/solvers/sketched.py — ISSUE 12).

Two tiers, per the tier-1 budget: engine mechanics on the smallest
shapes (<= 60x24, restarts <= 8), and the STATISTICAL agreement gate vs
the exact engine on the bundled 20+20x1000 two-group design — ARI of
the consensus memberships across >= 5 seeds at the dataset's true rank,
threshold recorded below. Heavier seed-sweep agreement runs are marked
``slow``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.agreement import consensus_agreement
from nmfx.api import nmfconsensus
from nmfx.config import InitConfig, SketchConfig, SolverConfig
from nmfx.datasets import two_group_matrix
from nmfx.solvers import sketched as sk
from nmfx.solvers.base import StopReason
from nmfx.sweep import resolve_engine_family, sweep_one_k

#: the recorded agreement gate (acceptance criterion): consensus
#: memberships of the sketched vs exact pipelines on the bundled
#: dataset at its TRUE rank (k=2), across the seeds below. Measured
#: headroom: ARI == 1.0 on every (seed, sketch-dim) probed during
#: development; the gate leaves room for platform reduction-order
#: drift without ever admitting a wrong clustering (ARI 0.9 on 40
#: samples = at most one boundary sample swapped).
AGREEMENT_SEEDS = (1, 2, 3, 4, 5)
ARI_GATE_MIN = 0.9
ARI_GATE_MEAN = 0.95
RHO_GAP_GATE = 0.12


def small_matrix():
    return two_group_matrix(n_genes=60, n_per_group=12, seed=0)


# -- engine mechanics (smallest shapes) ---------------------------------
def test_backend_validation():
    SolverConfig(algorithm="mu", backend="sketched")
    SolverConfig(algorithm="hals", backend="sketched")
    with pytest.raises(ValueError, match="sketched"):
        SolverConfig(algorithm="als", backend="sketched")
    with pytest.raises(ValueError, match="sketch.dim"):
        SketchConfig(dim=0)
    with pytest.raises(ValueError, match="screen_iters"):
        SketchConfig(screen_iters=0)


def test_engine_family_resolution():
    assert resolve_engine_family(
        SolverConfig(backend="sketched")) == "sketched"
    assert resolve_engine_family(
        SolverConfig(screen=True, screen_keep=2)) == "vmap"


def test_resolve_dim_clamps():
    cfg = SolverConfig(backend="sketched")
    assert sk.resolve_dim(cfg, 1000, 500, 3) == 40  # floor of the auto rule
    assert sk.resolve_dim(cfg, 1000, 500, 10) == 48  # 4k+8 past the floor
    assert sk.resolve_dim(cfg, 1000, 10, 3) == 10  # clamped to n
    assert sk.resolve_dim(
        dataclasses.replace(cfg, sketch=SketchConfig(dim=6)),
        1000, 500, 3) == 6
    # never below k+1 (the sketch must oversample the rank)
    assert sk.resolve_dim(
        dataclasses.replace(cfg, sketch=SketchConfig(dim=2)),
        1000, 500, 5) == 6


@pytest.mark.parametrize("algorithm", ["mu", "hals"])
def test_sketched_sweep_runs_and_reduces_residual(algorithm):
    a = small_matrix()
    cfg = SolverConfig(algorithm=algorithm, max_iter=200,
                       backend="sketched")
    key = jax.random.fold_in(jax.random.key(123), 2)
    out = sweep_one_k(a, key, 2, 6, cfg, InitConfig())
    dn = np.asarray(out.dnorms)
    assert dn.shape == (6,)
    assert np.all(np.isfinite(dn))
    # the final dnorm is the UNCOMPRESSED residual; from uniform random
    # init on this design the raw RMS starts ~O(1) — any real solve
    # lands far below it
    assert dn.mean() < 0.5
    labels = np.asarray(out.labels)
    assert labels.shape == (6, 24)
    assert set(np.unique(labels)) <= {0, 1}
    assert np.asarray(out.consensus).shape == (24, 24)


def test_sketched_deterministic_and_batch_independent():
    """A given (seed, k, restart) factorizes identically across calls
    and across batch compositions (the canonical-key-chain contract the
    exact engines carry, extended to the per-restart projections)."""
    a = small_matrix()
    cfg = SolverConfig(algorithm="mu", max_iter=120, backend="sketched")
    key = jax.random.fold_in(jax.random.key(7), 2)
    out1 = sweep_one_k(a, key, 2, 6, cfg, InitConfig())
    out2 = sweep_one_k(a, key, 2, 6, cfg, InitConfig())
    assert np.array_equal(np.asarray(out1.dnorms),
                          np.asarray(out2.dnorms))
    assert np.array_equal(np.asarray(out1.labels),
                          np.asarray(out2.labels))
    # prefix stability: the first 4 restarts of an 6-restart sweep are
    # the 4-restart sweep (split is prefix-stable; the fold_in-derived
    # sketch keys ride each restart's own key)
    out4 = sweep_one_k(a, key, 2, 4, cfg, InitConfig())
    assert np.array_equal(np.asarray(out1.dnorms)[:4],
                          np.asarray(out4.dnorms))


def test_momentum_off_runs():
    a = small_matrix()
    cfg = SolverConfig(algorithm="mu", max_iter=120, backend="sketched",
                       sketch=SketchConfig(momentum=False))
    key = jax.random.fold_in(jax.random.key(3), 2)
    out = sweep_one_k(a, key, 2, 4, cfg, InitConfig())
    assert np.all(np.isfinite(np.asarray(out.dnorms)))


def test_sketched_result_is_quality_tagged():
    a = small_matrix()
    res = nmfconsensus(a, ks=(2,), restarts=4, seed=1,
                       solver_cfg=SolverConfig(algorithm="mu",
                                               max_iter=120,
                                               backend="sketched"),
                       use_mesh=False)
    assert res.quality == "sketched"
    assert "sketched" in res.summary()
    exact = nmfconsensus(a, ks=(2,), restarts=4, seed=1,
                         solver_cfg=SolverConfig(algorithm="mu",
                                                 max_iter=120),
                         use_mesh=False)
    assert exact.quality == "exact"


def test_quality_tag_roundtrips_through_save_load(tmp_path):
    a = small_matrix()
    res = nmfconsensus(a, ks=(2,), restarts=3, seed=1,
                       solver_cfg=SolverConfig(algorithm="mu",
                                               max_iter=100,
                                               backend="sketched"),
                       use_mesh=False)
    path = str(tmp_path / "res.npz")
    res.save(path)
    from nmfx.api import ConsensusResult

    loaded = ConsensusResult.load(path)
    assert loaded.quality == "sketched"


def test_sketched_refuses_bit_exact_surfaces(tmp_path):
    """Every surface whose contract is bit-exact replay refuses the
    statistical engine loudly (the compose-guard class the CLI also
    enforces)."""
    from nmfx.config import CheckpointConfig

    a = small_matrix()
    cfg = SolverConfig(algorithm="mu", max_iter=100, backend="sketched")
    with pytest.raises(ValueError, match="sketched"):
        nmfconsensus(a, ks=(2,), restarts=3, solver_cfg=cfg,
                     checkpoint=CheckpointConfig(str(tmp_path / "ck")),
                     use_mesh=False)
    # the exec cache must refuse to serve it (grid_exec_ok gate)
    from nmfx.exec_cache import ExecCache
    from nmfx.config import ConsensusConfig

    assert not ExecCache().cacheable(
        ConsensusConfig(ks=(2,), restarts=3), cfg, None)


def test_model_flops_compression():
    """The analytic accounting the bench stage records: at north-star-
    like shapes the sketched per-iteration FLOPs are a small fraction
    of the exact engine's. Since ISSUE 13 the exact model lives in the
    costmodel registry (bench's local `_MODEL_FLOPS` trio is gone)."""
    from nmfx.obs import costmodel

    m, n, k = 5000, 500, 10
    r = sk.resolve_dim(SolverConfig(backend="sketched"), m, n, k)
    mu_flops = costmodel.iteration_flops("mu", "vmap", m, n, k)
    ratio = mu_flops / sk.sketched_model_flops(m, n, k, r)
    assert ratio > 5.0  # ~4mnk vs ~4rk(m+n): n/r-ish compression


# -- the statistical agreement gate (acceptance criterion) --------------
def _bundled_agreement(seeds, ks, restarts, max_iter):
    a = two_group_matrix(n_genes=1000, n_per_group=20, seed=123)
    exact = SolverConfig(algorithm="mu", max_iter=max_iter)
    sketch = dataclasses.replace(exact, backend="sketched")
    reports = {}
    for s in seeds:
        re_ = nmfconsensus(a, ks=ks, restarts=restarts, seed=s,
                           solver_cfg=exact, use_mesh=False)
        rs_ = nmfconsensus(a, ks=ks, restarts=restarts, seed=s,
                           solver_cfg=sketch, use_mesh=False)
        assert rs_.quality == "sketched"
        reports[s] = consensus_agreement(re_, rs_)
    return reports


def test_agreement_gate_bundled_dataset():
    """THE pinned gate: sketched vs exact on the bundled 20+20x1000
    design at its true rank k=2, ARI of the consensus memberships
    across 5 seeds — min >= 0.9, mean >= 0.95, |d rho| <= 0.12
    (thresholds recorded at module top; measured development headroom:
    ARI 1.0 on every seed)."""
    reports = _bundled_agreement(AGREEMENT_SEEDS, (2,), 6, 300)
    aris = [rep["per_k"][2]["ari"] for rep in reports.values()]
    gaps = [rep["per_k"][2]["rho_gap"] for rep in reports.values()]
    assert min(aris) >= ARI_GATE_MIN, (aris, reports)
    assert float(np.mean(aris)) >= ARI_GATE_MEAN, aris
    assert max(gaps) <= RHO_GAP_GATE, gaps


@pytest.mark.slow
def test_agreement_gate_heavy():
    """The heavier seed-sweep: more seeds, the over-clustered rank
    included (where surplus-cluster near-ties legitimately drift — the
    same class the hardware gate bounds), longer budgets."""
    reports = _bundled_agreement(tuple(range(1, 9)), (2, 3), 8, 500)
    aris2 = [rep["per_k"][2]["ari"] for rep in reports.values()]
    aris3 = [rep["per_k"][3]["ari"] for rep in reports.values()]
    assert min(aris2) >= ARI_GATE_MIN
    # over-clustered band: far above chance, below exact-rank crispness
    assert float(np.mean(aris3)) >= 0.5


# -- recompute-by-key and the solve() guard -----------------------------
def test_solve_refuses_sketched_and_screen():
    from nmfx.solvers.base import solve

    a = np.ones((8, 6), np.float32)
    w0 = np.ones((8, 2), np.float32)
    h0 = np.ones((2, 6), np.float32)
    with pytest.raises(ValueError, match="per-restart key"):
        solve(a, w0, h0, SolverConfig(algorithm="mu",
                                      backend="sketched"))
    with pytest.raises(ValueError, match="sweep layer"):
        solve(a, w0, h0, SolverConfig(algorithm="mu", screen=True,
                                      screen_keep=2))


def test_restart_factors_reproduces_sketched_lane():
    """The recompute-by-key contract extended to sketches: the sweep's
    projections fold off the canonical restart key, so restart_factors
    with the sketched config reproduces a sweep lane — same draws,
    same trajectory, within float tolerance (solo vs vmapped GEMM
    tilings reorder reductions — the whole-grid/per-k equivalence
    class; bit-exact recompute is an exact-engine property)."""
    from nmfx import restart_factors

    a = small_matrix()
    cfg = SolverConfig(algorithm="mu", max_iter=100, backend="sketched")
    key = jax.random.fold_in(jax.random.key(123), 2)
    out = sweep_one_k(a, key, 2, 4, cfg, InitConfig())
    for i in (0, 3):
        r = restart_factors(a, 2, i, restarts=4, seed=123,
                            solver_cfg=cfg)
        np.testing.assert_allclose(np.asarray(r.dnorm),
                                   np.asarray(out.dnorms)[i],
                                   rtol=1e-4)
        # trajectory-level identity: the iteration count (a stop
        # decision) matches, so this is the same solve, not merely a
        # nearby one
        assert int(r.iterations) == int(np.asarray(out.iterations)[i])


def test_nmf_sketched_runs_and_is_deterministic():
    from nmfx import nmf

    a = small_matrix()
    cfg = SolverConfig(algorithm="mu", max_iter=100, backend="sketched")
    r1 = nmf(a, 2, seed=3, solver_cfg=cfg)
    r2 = nmf(a, 2, seed=3, solver_cfg=cfg)
    assert np.asarray(r1.w).tobytes() == np.asarray(r2.w).tobytes()
    with pytest.raises(ValueError, match="no pool"):
        nmf(a, 2, solver_cfg=SolverConfig(algorithm="mu", screen=True,
                                          screen_keep=2))


# -- StopReason surface -------------------------------------------------
def test_screened_stop_reason_value_is_stable():
    # persisted in registries/records: the enum value is API
    assert int(StopReason.SCREENED) == 6
    assert int(StopReason.NUMERIC_FAULT) == 5
