"""Consensus/connectivity reduction tests (reference nmf.r:121-144)."""

import jax.numpy as jnp
import numpy as np

from nmfx.consensus import connectivity, consensus_matrix, labels_from_h


def test_labels_argmax_argmin():
    h = jnp.array([[0.1, 0.9, 0.5],
                   [0.8, 0.2, 0.6]])
    np.testing.assert_array_equal(labels_from_h(h, "argmax"), [1, 0, 1])
    np.testing.assert_array_equal(labels_from_h(h, "argmin"), [0, 1, 0])


def test_connectivity_matches_outer_equality():
    labels = jnp.array([0, 1, 0, 2])
    c = np.asarray(connectivity(labels))
    expect = np.equal.outer([0, 1, 0, 2], [0, 1, 0, 2]).astype(float)
    np.testing.assert_array_equal(c, expect)


def test_consensus_matches_naive_loop():
    # on-device einsum reduction == the reference's Reduce('+', outer(l,l,==))
    rng = np.random.default_rng(3)
    labels = rng.integers(0, 4, size=(11, 9))
    cons = np.asarray(consensus_matrix(jnp.asarray(labels), 4))
    naive = np.zeros((9, 9))
    for l in labels:
        naive += np.equal.outer(l, l)
    naive /= len(labels)
    np.testing.assert_allclose(cons, naive, atol=1e-6)


def test_consensus_diagonal_is_one():
    labels = jnp.zeros((5, 7), jnp.int32)
    cons = np.asarray(consensus_matrix(labels, 3))
    np.testing.assert_allclose(np.diag(cons), 1.0)
    np.testing.assert_allclose(cons, 1.0)  # identical labels => all ones
