"""End-to-end parity against the reference BINARY (not a transliteration).

``tests/golden_ref/reference_mu_fixture.npz`` holds factors, argmin labels,
consensus matrices, and scipy-computed cophenetic rho produced by the
reference's compiled ``nmf_mu`` (ctypes, R ``.C("nmf_mu", DUP=F)`` protocol
— see tests/golden_ref/generate_reference_fixture.py for the exact
protocol and regeneration recipe) on the bundled ``20+20x1000.gct`` at a
fixed 300-iteration budget from fixed W0/H0.

nmfx must reproduce it from the same inputs in f64: factors to tight
tolerance (different f64 BLAS — XLA vs netlib — reorder reductions; 300
multiplicative iterations amplify nothing pathological), labels and
consensus EXACTLY, rho to float tolerance. Runs in a subprocess because
``jax_enable_x64`` is global (same pattern as tests/test_x64_parity.py).
"""

import os
import subprocess
import sys
import textwrap

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(_TESTS_DIR, "golden_ref", "reference_mu_fixture.npz")


def test_reproduces_reference_binary_run():
    gct = os.environ.get("NMFX_REFERENCE_GCT",
                         "/root/reference/20+20x1000.gct")
    if not os.path.exists(gct):
        pytest.skip(f"reference fixture not found at {gct} "
                    "(set NMFX_REFERENCE_GCT)")
    code = f"""
    import jax
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from nmfx.config import SolverConfig
    from nmfx.cophenetic import rank_selection
    from nmfx.io import read_gct
    from nmfx.solvers.base import solve

    fx = np.load({FIXTURE!r})
    ks = tuple(int(k) for k in fx["ks"])
    restarts = int(fx["restarts"])
    maxiter = int(fx["maxiter"])
    ds = read_gct({gct!r})
    a = np.asarray(ds.values, np.float64)
    assert list(a.shape) == list(fx["shape"])

    # the reference harness replicates the R layer's protocol: init comes
    # from the caller (nmf.r:37-38), only the class-stability stop is live
    # (and cannot fire inside 300 iterations), tol checks are commented out
    cfg = SolverConfig(algorithm="mu", max_iter=maxiter, dtype="float64",
                       use_tol_checks=False, class_flip_tol=0.0)
    rhos = {{}}
    for k in ks:
        # rng draw order: the generator draws w0 THEN h0 per restart from
        # one per-(k, r) stream — reproduce that exactly
        w0s = np.empty((restarts, a.shape[0], k))
        h0s = np.empty((restarts, k, a.shape[1]))
        for r in range(restarts):
            rng = np.random.default_rng(1000 * k + r)
            w0s[r] = rng.random((a.shape[0], k))
            h0s[r] = rng.random((k, a.shape[1]))
        res = jax.vmap(lambda w0, h0: solve(a, w0, h0, cfg))(
            jnp.asarray(w0s), jnp.asarray(h0s))
        assert np.all(np.asarray(res.iterations) == maxiter)
        labels = np.argmin(np.asarray(res.h), axis=1)  # R rule (Q3)
        for r in range(restarts):
            href = fx[f"h_k{{k}}_r{{r}}"]
            np.testing.assert_allclose(np.asarray(res.h)[r], href,
                                       rtol=1e-7, atol=1e-9)
        np.testing.assert_allclose(np.asarray(res.w)[0], fx[f"w_k{{k}}_r0"],
                                   rtol=1e-7, atol=1e-9)
        np.testing.assert_array_equal(labels, fx[f"labels_k{{k}}"])
        cons = (labels[:, :, None] == labels[:, None, :]).mean(0)
        np.testing.assert_array_equal(cons, fx[f"consensus_k{{k}}"])
        # rho: the fixture's value is a scipy oracle on the same consensus.
        # Consensus matrices are extremely tie-heavy (k=3: 7 distinct
        # distances over 780 pairs), and average-linkage merge order under
        # ties is implementation-defined — scipy's nn-chain, nmfx, and R
        # hclust may each produce a different (all valid) tree with rho
        # differing at the ~3e-4 level. The consensus itself (the
        # binary-derived object) is asserted EXACT above; rho gets a
        # tie-ambiguity band, plus the rank-table ordering the reference
        # user actually consumes (k=2 must win on this 2-group design).
        rho, _, _ = rank_selection(cons, k)
        np.testing.assert_allclose(rho, float(fx[f"rho_k{{k}}"]),
                                   atol=1e-3)
        rhos[k] = rho
        print(f"k={{k}} OK rho={{rho:.6f}}")
    assert max(rhos, key=rhos.get) == 2, rhos
    print("OK")
    """
    proc = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                          capture_output=True, text=True, timeout=600,
                          cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "OK" in proc.stdout


@pytest.mark.parametrize("engine", ["grid", "sched", "sched_pallas"])
def test_reference_binary_through_grid_engines(engine):
    """The same reference-binary fixture driven through the execution
    engines users actually get (VERDICT r3 #5): the whole mixed-rank
    (k=2..5 × 10 restarts) grid as ONE zero-padded job batch through
    ``mu_grid`` and ``mu_sched`` — the scheduler with a deliberately tiny
    slot pool (7 slots for 40 jobs) so every job beyond the first seven
    rides the evict/reload path that round 3's pallas kernel corrupted.

    The f64 engines (grid, sched-dense) must match the reference binary's
    factors to the same tight tolerance as the vmap path plus labels and
    consensus EXACTLY; the pallas engine accumulates in f32 inside its
    kernels (interpret mode on CPU), so its factors drift at f32 scale —
    for it the binary-parity claim is the user-visible one: labels and
    consensus exact, rho in the tie-ambiguity band.
    """
    gct = os.environ.get("NMFX_REFERENCE_GCT",
                         "/root/reference/20+20x1000.gct")
    if not os.path.exists(gct):
        pytest.skip(f"reference fixture not found at {gct} "
                    "(set NMFX_REFERENCE_GCT)")
    code = f"""
    import jax
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from nmfx.config import SolverConfig
    from nmfx.io import read_gct

    engine = {engine!r}
    fx = np.load({FIXTURE!r})
    ks = tuple(int(k) for k in fx["ks"])
    restarts = int(fx["restarts"])
    maxiter = int(fx["maxiter"])
    a = np.asarray(read_gct({gct!r}).values, np.float64)
    m, n = a.shape
    k_max = max(ks)

    # one dense zero-padded job batch, rank-major — the grid engines'
    # production layout (sweep._build_grid_exec_sweep_fn)
    jobs = [(k, r) for k in ks for r in range(restarts)]
    w0 = np.zeros((len(jobs), m, k_max))
    h0 = np.zeros((len(jobs), k_max, n))
    for j, (k, r) in enumerate(jobs):
        rng = np.random.default_rng(1000 * k + r)
        w0[j, :, :k] = rng.random((m, k))
        h0[j, :k, :] = rng.random((k, n))

    backend = "pallas" if engine == "sched_pallas" else "auto"
    cfg = SolverConfig(algorithm="mu", max_iter=maxiter, dtype="float64",
                       use_tol_checks=False, class_flip_tol=0.0,
                       backend=backend)
    job_ks = tuple(k for k, _r in jobs)
    if engine == "grid":
        from nmfx.ops.grid_mu import mu_grid
        res = mu_grid(a, jnp.asarray(w0), jnp.asarray(h0), cfg,
                      job_ks=job_ks)
    else:
        from nmfx.ops.sched_mu import mu_sched
        res = mu_sched(a, jnp.asarray(w0), jnp.asarray(h0), cfg, slots=7,
                       job_ks=job_ks)
    assert np.all(np.asarray(res.iterations) == maxiter)

    h = np.asarray(res.h)
    w = np.asarray(res.w)
    for k in ks:
        base_j = jobs.index((k, 0))
        labels = np.stack([np.argmin(h[base_j + r, :k, :], axis=0)
                           for r in range(restarts)])
        np.testing.assert_array_equal(labels, fx[f"labels_k{{k}}"])
        cons = (labels[:, :, None] == labels[:, None, :]).mean(0)
        np.testing.assert_array_equal(cons, fx[f"consensus_k{{k}}"])
        if engine != "sched_pallas":
            for r in range(restarts):
                np.testing.assert_allclose(
                    h[base_j + r, :k, :], fx[f"h_k{{k}}_r{{r}}"],
                    rtol=1e-7, atol=1e-9)
            np.testing.assert_allclose(w[base_j, :, :k], fx[f"w_k{{k}}_r0"],
                                       rtol=1e-7, atol=1e-9)
        from nmfx.cophenetic import rank_selection
        rho, _, _ = rank_selection(cons, k)
        np.testing.assert_allclose(rho, float(fx[f"rho_k{{k}}"]), atol=1e-3)
        print(f"k={{k}} OK")
    print("OK")
    """
    proc = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                          capture_output=True, text=True, timeout=600,
                          cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "OK" in proc.stdout
