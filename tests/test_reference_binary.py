"""End-to-end parity against the reference BINARY (not a transliteration).

``tests/golden_ref/reference_mu_fixture.npz`` holds factors, argmin labels,
consensus matrices, and scipy-computed cophenetic rho produced by the
reference's compiled ``nmf_mu`` (ctypes, R ``.C("nmf_mu", DUP=F)`` protocol
— see tests/golden_ref/generate_reference_fixture.py for the exact
protocol and regeneration recipe) on the bundled ``20+20x1000.gct`` at a
fixed 300-iteration budget from fixed W0/H0.

nmfx must reproduce it from the same inputs in f64: factors to tight
tolerance (different f64 BLAS — XLA vs netlib — reorder reductions; 300
multiplicative iterations amplify nothing pathological), labels and
consensus EXACTLY, rho to float tolerance. Runs in a subprocess because
``jax_enable_x64`` is global (same pattern as tests/test_x64_parity.py).
"""

import os
import subprocess
import sys
import textwrap

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
FIXTURE = os.path.join(_TESTS_DIR, "golden_ref", "reference_mu_fixture.npz")


def test_reproduces_reference_binary_run():
    gct = os.environ.get("NMFX_REFERENCE_GCT",
                         "/root/reference/20+20x1000.gct")
    if not os.path.exists(gct):
        pytest.skip(f"reference fixture not found at {gct} "
                    "(set NMFX_REFERENCE_GCT)")
    code = f"""
    import jax
    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from nmfx.config import SolverConfig
    from nmfx.cophenetic import rank_selection
    from nmfx.io import read_gct
    from nmfx.solvers.base import solve

    fx = np.load({FIXTURE!r})
    ks = tuple(int(k) for k in fx["ks"])
    restarts = int(fx["restarts"])
    maxiter = int(fx["maxiter"])
    ds = read_gct({gct!r})
    a = np.asarray(ds.values, np.float64)
    assert list(a.shape) == list(fx["shape"])

    # the reference harness replicates the R layer's protocol: init comes
    # from the caller (nmf.r:37-38), only the class-stability stop is live
    # (and cannot fire inside 300 iterations), tol checks are commented out
    cfg = SolverConfig(algorithm="mu", max_iter=maxiter, dtype="float64",
                       use_tol_checks=False, class_flip_tol=0.0)
    rhos = {{}}
    for k in ks:
        # rng draw order: the generator draws w0 THEN h0 per restart from
        # one per-(k, r) stream — reproduce that exactly
        w0s = np.empty((restarts, a.shape[0], k))
        h0s = np.empty((restarts, k, a.shape[1]))
        for r in range(restarts):
            rng = np.random.default_rng(1000 * k + r)
            w0s[r] = rng.random((a.shape[0], k))
            h0s[r] = rng.random((k, a.shape[1]))
        res = jax.vmap(lambda w0, h0: solve(a, w0, h0, cfg))(
            jnp.asarray(w0s), jnp.asarray(h0s))
        assert np.all(np.asarray(res.iterations) == maxiter)
        labels = np.argmin(np.asarray(res.h), axis=1)  # R rule (Q3)
        for r in range(restarts):
            href = fx[f"h_k{{k}}_r{{r}}"]
            np.testing.assert_allclose(np.asarray(res.h)[r], href,
                                       rtol=1e-7, atol=1e-9)
        np.testing.assert_allclose(np.asarray(res.w)[0], fx[f"w_k{{k}}_r0"],
                                   rtol=1e-7, atol=1e-9)
        np.testing.assert_array_equal(labels, fx[f"labels_k{{k}}"])
        cons = (labels[:, :, None] == labels[:, None, :]).mean(0)
        np.testing.assert_array_equal(cons, fx[f"consensus_k{{k}}"])
        # rho: the fixture's value is a scipy oracle on the same consensus.
        # Consensus matrices are extremely tie-heavy (k=3: 7 distinct
        # distances over 780 pairs), and average-linkage merge order under
        # ties is implementation-defined — scipy's nn-chain, nmfx, and R
        # hclust may each produce a different (all valid) tree with rho
        # differing at the ~3e-4 level. The consensus itself (the
        # binary-derived object) is asserted EXACT above; rho gets a
        # tie-ambiguity band, plus the rank-table ordering the reference
        # user actually consumes (k=2 must win on this 2-group design).
        rho, _, _ = rank_selection(cons, k)
        np.testing.assert_allclose(rho, float(fx[f"rho_k{{k}}"]),
                                   atol=1e-3)
        rhos[k] = rho
        print(f"k={{k}} OK rho={{rho:.6f}}")
    assert max(rhos, key=rhos.get) == 2, rhos
    print("OK")
    """
    proc = subprocess.run([sys.executable, "-c", textwrap.dedent(code)],
                          capture_output=True, text=True, timeout=600,
                          cwd="/root/repo")
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "OK" in proc.stdout
